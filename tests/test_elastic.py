"""Elastic recovery: crash mid-training, restore from checkpoint, finish —
and end bit-identical to an uninterrupted run (SURVEY.md §4 parity rule
applied to the failure path)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_tensorflow_guide_tpu.train.checkpoint import Checkpointer
from distributed_tensorflow_guide_tpu.train.elastic import (
    TooManyRestarts,
    run_with_recovery,
)
from distributed_tensorflow_guide_tpu.train.hooks import StopAtStepHook

TOTAL_STEPS = 20
CKPT_EVERY = 5


def _step_fn(state, batch):
    # toy GD on sum-of-squares; deterministic in (state, batch)
    params = state["params"]
    grad = 2 * params + batch
    new = {"params": params - 0.01 * grad}
    return new, {"loss": jnp.sum(params ** 2)}


def _make_data(start_step):
    # deterministic stream keyed by step — resume must not replay
    return (jnp.full((4,), float(s)) for s in range(start_step, 10_000))


def _init_state():
    return {"params": jnp.ones((4,))}


def _run(crash_at=None, tmpdir=None, max_restarts=3):
    crashed = []

    def step(state, batch):
        # host-side fault injection: raise exactly once at `crash_at`
        if crash_at is not None and not crashed:
            # batch value encodes the step (see _make_data)
            if int(batch[0]) == crash_at:
                crashed.append(True)
                raise RuntimeError("injected crash")
        return _step_fn(state, batch)

    ckpt = Checkpointer(tmpdir, max_to_keep=2)
    try:
        return run_with_recovery(
            step,
            _init_state(),
            _make_data,
            ckpt,
            hooks=[StopAtStepHook(TOTAL_STEPS)],
            checkpoint_every=CKPT_EVERY,
            max_restarts=max_restarts,
        )
    finally:
        ckpt.close()


def test_crash_resume_matches_uninterrupted(tmp_path):
    clean = _run(tmpdir=tmp_path / "clean")
    crashed = _run(crash_at=12, tmpdir=tmp_path / "crashed")
    np.testing.assert_array_equal(
        np.asarray(clean["params"]), np.asarray(crashed["params"])
    )


def test_restart_budget_enforced(tmp_path):
    def always_fail(state, batch):
        raise RuntimeError("permanent failure")

    ckpt = Checkpointer(tmp_path / "fail", max_to_keep=1)
    try:
        with pytest.raises(TooManyRestarts):
            run_with_recovery(
                always_fail,
                _init_state(),
                _make_data,
                ckpt,
                hooks=[StopAtStepHook(TOTAL_STEPS)],
                checkpoint_every=CKPT_EVERY,
                max_restarts=2,
            )
    finally:
        ckpt.close()


def test_corrupt_newest_checkpoint_does_not_crash_loop_recovery(tmp_path):
    """Regression (round-10 satellite): run_with_recovery used to restore
    only the LATEST step — a truncated newest checkpoint made every restart
    attempt die on the same bad files until max_restarts, losing a run that
    had perfectly good older checkpoints. The restore ladder must fall back
    (and log the skipped step), then extend the run to bitwise parity."""
    import logging

    from distributed_tensorflow_guide_tpu.testing.chaos import (
        corrupt_checkpoint,
    )

    d = tmp_path / "trunc"
    _run(tmpdir=d)  # saves 5/10/15/20; max_to_keep=2 keeps 15 and 20
    corrupted_step, _ = corrupt_checkpoint(d, mode="truncate")
    assert corrupted_step == 20

    ckpt = Checkpointer(d, max_to_keep=2)
    records = []
    handler = logging.Handler()
    handler.emit = lambda rec: records.append(rec.getMessage())
    logging.getLogger("dtg.train").addHandler(handler)
    try:
        final = run_with_recovery(
            _step_fn, _init_state(), _make_data, ckpt,
            hooks=[StopAtStepHook(30)], checkpoint_every=CKPT_EVERY,
        )
    finally:
        logging.getLogger("dtg.train").removeHandler(handler)
        ckpt.close()
    # fallback restored step 15 and logged the skipped step number
    assert any("restore ladder" in m and "[20]" in m for m in records)
    state = _init_state()
    for s, batch in zip(range(30), _make_data(0)):
        state, _ = _step_fn(state, batch)
    np.testing.assert_array_equal(
        np.asarray(final["params"]), np.asarray(state["params"])
    )


def test_resume_from_existing_checkpoint_dir(tmp_path):
    # run to step 20, then extend the same dir to 30 — warm-start resume
    d = tmp_path / "extend"
    _run(tmpdir=d)
    ckpt = Checkpointer(d, max_to_keep=2)
    try:
        final = run_with_recovery(
            _step_fn,
            _init_state(),
            _make_data,
            ckpt,
            hooks=[StopAtStepHook(30)],
            checkpoint_every=CKPT_EVERY,
        )
    finally:
        ckpt.close()
    # oracle: 30 uninterrupted steps
    state = _init_state()
    for s, batch in zip(range(30), _make_data(0)):
        state, _ = _step_fn(state, batch)
    np.testing.assert_allclose(
        np.asarray(final["params"]), np.asarray(state["params"]), rtol=1e-6
    )


# ---- multi-process elastic recovery ----------------------------------------
# Run 1: 2-process DP training crashes abruptly (os._exit, like an OOM-kill)
# after a checkpoint landed. Run 2: a fresh launch resumes from the latest
# checkpoint and finishes. Final params must match an uninterrupted reference
# — the reference's MonitoredTrainingSession restart-from-checkpoint story,
# but actually tested, across real process boundaries.

MP_TOTAL = 12
MP_CKPT_EVERY = 4
MP_CRASH_AFTER = 7  # > first checkpoint (4), before the next (8)


def _mp_elastic_problem():
    rng = np.random.RandomState(3)
    gx = rng.randn(8, 4).astype(np.float32)
    gw = np.arange(4, dtype=np.float32)
    return gx, gx @ gw


def _target_elastic_dp(ckpt_dir, crash_after):
    import os

    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax
    from flax.training import train_state

    from distributed_tensorflow_guide_tpu.core.mesh import MeshSpec, build_mesh
    from distributed_tensorflow_guide_tpu.parallel.data_parallel import (
        DataParallel,
    )
    from distributed_tensorflow_guide_tpu.train.checkpoint import (
        Checkpointer,
        CheckpointHook,
    )
    from distributed_tensorflow_guide_tpu.train.hooks import (
        BaseHook,
        StopAtStepHook,
    )
    from distributed_tensorflow_guide_tpu.train.loop import TrainLoop

    mesh = build_mesh(MeshSpec(data=-1))
    dp = DataParallel(mesh)
    gx, gy = _mp_elastic_problem()
    per = len(gx) // jax.process_count()
    lo = jax.process_index() * per

    def make_batch(s):
        # step-keyed deterministic stream: scale inputs by (1 + s/10)
        f = 1.0 + s / 10.0
        return {"x": gx[lo:lo + per] * f, "y": gy[lo:lo + per] * f}

    def loss_fn(params, batch):
        pred = batch["x"] @ params["w"]
        return jnp.mean((pred - batch["y"]) ** 2), {}

    state0 = dp.replicate(train_state.TrainState.create(
        apply_fn=lambda v, x: x @ v["params"]["w"],
        params={"w": jnp.zeros(4, jnp.float32)},
        tx=optax.sgd(0.05),
    ))
    ckpt = Checkpointer(ckpt_dir, max_to_keep=2)
    start = ckpt.latest_step() or 0
    state = ckpt.restore(state0) if start else state0

    class CrashHook(BaseHook):
        def after_step(self, step, metrics):
            if crash_after >= 0 and step + 1 == crash_after:
                ckpt.wait()  # make the async checkpoint durable first
                print("CRASHING", flush=True)
                os._exit(1)  # abrupt, like a kill — no atexit barriers

    loop = TrainLoop(
        dp.make_train_step(loss_fn, donate=False),
        state,
        (dp.shard_batch(make_batch(s)) for s in range(start, 10_000)),
        hooks=[CheckpointHook(ckpt, MP_CKPT_EVERY), CrashHook(),
               StopAtStepHook(MP_TOTAL)],
        start_step=start,
    )
    final = loop.run()
    ckpt.close()
    return {
        "resumed_from": start,
        "steps_done": loop.step,
        "w": np.asarray(final.params["w"]).tolist(),
    }


def test_multiprocess_crash_and_resume(tmp_path):
    from distributed_tensorflow_guide_tpu.runtime.multiprocess import (
        MultiProcessRunner,
        run_multiprocess,
    )

    ckpt_dir = str(tmp_path / "ckpt")
    # run 1: crashes at step MP_CRASH_AFTER (after the step-4 checkpoint)
    runner_results = MultiProcessRunner(
        _target_elastic_dp, 2, args=(ckpt_dir, MP_CRASH_AFTER),
        local_devices_per_process=2,
    ).start().join(raise_on_error=False)
    assert all(not r.ok for r in runner_results)
    assert any("CRASHING" in r.stdout for r in runner_results)

    # run 2: fresh processes resume from the durable checkpoint and finish
    results = run_multiprocess(
        _target_elastic_dp, 2, args=(ckpt_dir, -1),
        local_devices_per_process=2,
    )
    for r in results:
        assert r.result["resumed_from"] == MP_CKPT_EVERY
        assert r.result["steps_done"] == MP_TOTAL

    # parity with an uninterrupted single-process run of the same schedule
    gx, gy = _mp_elastic_problem()
    w = np.zeros(4, np.float32)
    for s in range(MP_TOTAL):
        f = 1.0 + s / 10.0
        x, y = gx * f, gy * f
        pred = x @ w
        w = w - 0.05 * (2.0 / len(x)) * x.T @ (pred - y)
    for r in results:
        assert r.result["w"] == pytest.approx(w.tolist(), rel=1e-4)


def test_preemption_sigterm_saves_and_resumes(tmp_path):
    """Graceful preemption: SIGTERM mid-run is deferred to the step
    boundary, a checkpoint labeled with the completed-step count is saved,
    the loop stops cleanly — and a fresh run resuming from it produces the
    SAME final state as an uninterrupted run (the crash-resume identity,
    but with zero lost steps)."""
    import os
    import signal

    from distributed_tensorflow_guide_tpu.train.elastic import PreemptionHook
    from distributed_tensorflow_guide_tpu.train.loop import TrainLoop

    # uninterrupted reference
    state = _init_state()
    loop = TrainLoop(_step_fn, state, _make_data(0),
                     hooks=[StopAtStepHook(TOTAL_STEPS)])
    ref = loop.run()

    # preempted run: SIGTERM arrives DURING step 3's compute
    ckpt = Checkpointer(tmp_path / "pre")
    hook = PreemptionHook(ckpt)

    def step(state, batch):
        if int(batch[0]) == 3:
            os.kill(os.getpid(), signal.SIGTERM)  # handler defers to flag
        return _step_fn(state, batch)

    original_handler = signal.getsignal(signal.SIGTERM)
    loop1 = TrainLoop(step, _init_state(), _make_data(0),
                      hooks=[StopAtStepHook(TOTAL_STEPS), hook])
    mid = loop1.run()
    assert hook.preempted_at == 4  # step 3 completed, label = count
    assert ckpt.latest_step() == 4
    assert loop1.step == 4  # stopped cleanly, no further steps ran
    # the ORIGINAL handler is back (bound methods compare by identity of
    # __self__/__func__, so == is the meaningful comparison)
    assert signal.getsignal(signal.SIGTERM) == original_handler

    # resume: restore label 4, continue to the end
    start = ckpt.latest_step()
    resumed = ckpt.restore(mid)
    loop2 = TrainLoop(_step_fn, resumed, _make_data(start),
                      hooks=[StopAtStepHook(TOTAL_STEPS)], start_step=start)
    final = loop2.run()
    np.testing.assert_allclose(np.asarray(final["params"]),
                               np.asarray(ref["params"]), rtol=1e-6)
    ckpt.close()


def test_preemption_sync_every_cadence_and_final_drain(tmp_path):
    """Round-4 advisor: with sync_every>1 the single-host path reacted every
    step while multi-host reacted only at agreement points, and a SIGTERM
    landing after the last agreement point was silently dropped. Now the
    cadence gates both paths identically and end() runs a final agreement
    drain, so a late flag still saves."""
    import os
    import signal

    from distributed_tensorflow_guide_tpu.train.elastic import PreemptionHook
    from distributed_tensorflow_guide_tpu.train.loop import TrainLoop

    # SIGTERM during step 3, cadence 50 > TOTAL_STEPS: no agreement point
    # is ever reached mid-run -> the hook must NOT stop the loop early, and
    # the end() drain must still save.
    ckpt = Checkpointer(tmp_path / "late")
    hook = PreemptionHook(ckpt, sync_every=50)

    def step(state, batch):
        if int(batch[0]) == 3:
            os.kill(os.getpid(), signal.SIGTERM)
        return _step_fn(state, batch)

    loop = TrainLoop(step, _init_state(), _make_data(0),
                     hooks=[StopAtStepHook(TOTAL_STEPS), hook])
    loop.run()
    assert loop.step == TOTAL_STEPS  # cadence held: no mid-run stop
    assert hook.preempted_at == TOTAL_STEPS  # drain saved at the end
    assert ckpt.latest_step() == TOTAL_STEPS
    # the drain retags the stop so later end-phase hooks (EvalHook) skip
    # grace-window-eating work even on this late-flag path
    assert loop.stop_reason == "preemption"
    ckpt.close()

    # cadence-aligned flag: acts at the agreement point, not before
    ckpt2 = Checkpointer(tmp_path / "aligned")
    hook2 = PreemptionHook(ckpt2, sync_every=4)

    def step2(state, batch):
        if int(batch[0]) == 0:
            os.kill(os.getpid(), signal.SIGTERM)
        return _step_fn(state, batch)

    loop2 = TrainLoop(step2, _init_state(), _make_data(0),
                      hooks=[StopAtStepHook(TOTAL_STEPS), hook2])
    loop2.run()
    # flagged at step 0 but the first agreement point is after step 3
    # (done == 4): the loop stops there, not at step 1
    assert hook2.preempted_at == 4
    assert loop2.step == 4
    ckpt2.close()


def test_preemption_handler_restored_after_crash(tmp_path):
    """A CRASHED loop must not leave the flag-only handler installed
    process-wide (it would silently swallow the cluster manager's real
    SIGTERM forever) — restoration runs in TrainLoop's cleanup phase,
    which fires on the crash path where end() deliberately does not."""
    import signal

    from distributed_tensorflow_guide_tpu.train.elastic import PreemptionHook
    from distributed_tensorflow_guide_tpu.train.loop import TrainLoop

    original = signal.getsignal(signal.SIGTERM)
    ckpt = Checkpointer(tmp_path / "crash")
    hook = PreemptionHook(ckpt)

    def bad_step(state, batch):
        raise RuntimeError("boom")

    loop = TrainLoop(bad_step, _init_state(), _make_data(0), hooks=[hook])
    with pytest.raises(RuntimeError, match="boom"):
        loop.run()
    assert signal.getsignal(signal.SIGTERM) == original
    ckpt.close()


def test_preemption_hook_reusable_across_runs(tmp_path):
    """A restarter reusing the hook instance: run 1 preempts and saves;
    run 2 with the SAME instance must be able to preempt again (begin
    resets the latch) and save its own later checkpoint."""
    import os
    import signal

    from distributed_tensorflow_guide_tpu.train.elastic import PreemptionHook
    from distributed_tensorflow_guide_tpu.train.loop import TrainLoop

    ckpt = Checkpointer(tmp_path / "reuse", max_to_keep=5)
    hook = PreemptionHook(ckpt)

    def make_step(kill_at):
        def step(state, batch):
            if int(batch[0]) == kill_at:
                os.kill(os.getpid(), signal.SIGTERM)
            return _step_fn(state, batch)

        return step

    loop1 = TrainLoop(make_step(2), _init_state(), _make_data(0),
                      hooks=[StopAtStepHook(TOTAL_STEPS), hook])
    mid = loop1.run()
    assert hook.preempted_at == 3

    start = ckpt.latest_step()
    loop2 = TrainLoop(make_step(6), ckpt.restore(mid), _make_data(start),
                      hooks=[StopAtStepHook(TOTAL_STEPS), hook],
                      start_step=start)
    loop2.run()
    assert hook.preempted_at == 7  # the reused instance preempted AGAIN
    assert ckpt.latest_step() == 7
    ckpt.close()


def test_preemption_handler_restored_when_later_hook_begin_raises(tmp_path):
    """If a hook AFTER PreemptionHook raises in begin(), the loop must
    still run cleanup() for the hooks already begun — otherwise the
    flag-only SIGTERM handler leaks process-wide before a single step
    ran."""
    import signal

    from distributed_tensorflow_guide_tpu.train.elastic import PreemptionHook
    from distributed_tensorflow_guide_tpu.train.loop import TrainLoop

    class _BadBegin:
        def begin(self, loop):
            raise PermissionError("cannot open metrics file")

        def after_step(self, step, metrics):
            pass

        def end(self, step):
            pass

    original = signal.getsignal(signal.SIGTERM)
    ckpt = Checkpointer(tmp_path / "bb")
    loop = TrainLoop(_step_fn, _init_state(), _make_data(0),
                     hooks=[PreemptionHook(ckpt), _BadBegin()])
    with pytest.raises(PermissionError):
        loop.run()
    assert signal.getsignal(signal.SIGTERM) == original
    ckpt.close()
