import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

import distributed_tensorflow_guide_tpu.collectives as cc

from distributed_tensorflow_guide_tpu.core.compat import shard_map  # noqa: E402


def test_psum_matches_sum(mesh8):
    x = jnp.arange(8.0)

    f = shard_map(
        lambda v: cc.psum(v, "data"),
        mesh=mesh8,
        in_specs=P("data"),
        out_specs=P("data"),
    )
    out = f(x)
    np.testing.assert_allclose(np.asarray(out), np.full(8, x.sum()))


def test_pmean(mesh8):
    x = jnp.arange(8.0)
    f = shard_map(
        lambda v: cc.pmean(v, "data"),
        mesh=mesh8,
        in_specs=P("data"),
        out_specs=P("data"),
    )
    np.testing.assert_allclose(np.asarray(f(x)), np.full(8, x.mean()))


def test_all_gather_tiled(mesh8):
    x = jnp.arange(16.0).reshape(8, 2)
    f = shard_map(
        lambda v: cc.all_gather(v, "data", tiled=True),
        mesh=mesh8,
        in_specs=P("data", None),
        out_specs=P(None, None),
        check_vma=False,  # all_gather output is replicated; checker can't infer it
    )
    np.testing.assert_allclose(np.asarray(f(x)), np.asarray(x))


def test_reduce_scatter_then_gather_is_allreduce(mesh8):
    x = jnp.arange(64.0).reshape(8, 8)

    def body(v):  # v: (1, 8) per device
        rs = cc.reduce_scatter(v, "data", scatter_axis=1)  # (1, 1): colsum shard
        return cc.all_gather(rs, "data", tiled=True, gather_axis=1)  # (1, 8)

    f = shard_map(body, mesh=mesh8, in_specs=P("data", None), out_specs=P("data", None))
    expected = np.asarray(x).sum(axis=0, keepdims=True).repeat(8, axis=0)
    np.testing.assert_allclose(np.asarray(f(x)), expected)


def test_ring_shift(mesh8):
    x = jnp.arange(8.0)
    f = shard_map(
        functools.partial(cc.ring_shift, axis="data", shift=1),
        mesh=mesh8,
        in_specs=P("data"),
        out_specs=P("data"),
    )
    out = np.asarray(f(x))
    np.testing.assert_allclose(out, np.roll(np.arange(8.0), 1))


def test_all_to_all_roundtrip(mesh8):
    x = jnp.arange(8 * 8.0).reshape(8, 8)

    def body(v):  # v: (1, 8) per device
        w = cc.all_to_all(v, "data", split_axis=1, concat_axis=0)  # (8, 1)
        return cc.all_to_all(w, "data", split_axis=0, concat_axis=1)

    f = shard_map(body, mesh=mesh8, in_specs=P("data", None), out_specs=P("data", None))
    np.testing.assert_allclose(np.asarray(f(x)), np.asarray(x))


def test_trace_comm_counts(mesh8):
    x = jnp.arange(8, dtype=jnp.float32)

    def body(v):
        v = cc.psum(v, "data")
        v = cc.pmean(v, "data")
        return v

    with cc.trace_comm() as rec:
        f = shard_map(body, mesh=mesh8, in_specs=P("data"), out_specs=P("data"))
        jax.jit(f).lower(x)  # force tracing inside the context
    assert rec.calls["psum[data]"] == 1
    assert rec.calls["pmean[data]"] == 1
    assert rec.bytes["psum[data]"] == 4  # one f32 per shard at trace time
    assert rec.total_calls() == 2


def test_axis_size(mesh8):
    f = shard_map(
        lambda v: v * cc.axis_size("data"),
        mesh=mesh8,
        in_specs=P("data"),
        out_specs=P("data"),
    )
    np.testing.assert_allclose(np.asarray(f(jnp.ones(8))), np.full(8, 8.0))
