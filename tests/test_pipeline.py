"""Config-5 coverage: GPipe pipeline parallelism over the pipe mesh axis.

The load-bearing test is parity: the pipelined step must produce the SAME
loss and gradients as an unpipelined run of the identical stacked-layer
model (pipelining is an execution schedule, not a different algorithm)."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax import lax

from distributed_tensorflow_guide_tpu.core.mesh import MeshSpec, build_mesh
from distributed_tensorflow_guide_tpu.models.transformer import (
    TransformerConfig,
)
from distributed_tensorflow_guide_tpu.parallel.pipeline import PipelinedLM

CFG = TransformerConfig(
    vocab_size=64, num_layers=4, num_heads=2, d_model=32, d_ff=64,
    max_len=16, causal=True, dtype=jnp.float32,
)


def _tokens(batch, seed=0):
    rng = np.random.RandomState(seed)
    return rng.randint(0, CFG.vocab_size, (batch, CFG.max_len)).astype(np.int32)


def _reference_loss(pp, params, tokens):
    """Unpipelined forward with the same stacked params."""
    x = pp.embedder.apply({"params": params["embed"]}, tokens)
    stages = params["stages"]
    if pp.virtual_chunks > 1:
        # interleaved stacking: row s*v + j holds chunk-stage k = j*P + s;
        # re-order rows to global layer order for the oracle
        P_, v = pp.n_stages, pp.virtual_chunks
        order = np.asarray([(k % P_) * v + k // P_ for k in range(P_ * v)])
        stages = jax.tree.map(lambda s: s[order], stages)
    flat = jax.tree.map(
        lambda s: s.reshape(-1, *s.shape[2:]), stages
    )

    def body(h, layer_params):
        return pp.block.apply({"params": layer_params}, h), None

    x, _ = lax.scan(body, x, flat)
    logits = pp.head.apply({"params": params["head"]}, x)
    logp = jax.nn.log_softmax(logits[:, :-1])
    ll = jnp.take_along_axis(logp, tokens[:, 1:][..., None], axis=-1)[..., 0]
    return -jnp.mean(ll)


@pytest.mark.parametrize("schedule", ["gpipe", "1f1b"])
@pytest.mark.parametrize("n_pipe,n_data", [(4, 1), (2, 2)])
def test_pipeline_matches_unpipelined(n_pipe, n_data, schedule):
    # model fills to 2 in both shapes: these four configs are ALSO the
    # tp>1 parity coverage for the v=1 schedules (TP-sharded stages,
    # vocab-parallel embedding + head under both gpipe and 1f1b)
    mesh = build_mesh(MeshSpec(data=n_data, pipe=n_pipe, model=8 // (n_pipe * n_data)))
    M = 4  # microbatches
    pp = PipelinedLM(mesh, CFG, num_microbatches=M, schedule=schedule)
    params = pp.init_params(jax.random.PRNGKey(0))
    tx = optax.sgd(0.1)
    opt_state = pp.init_opt_state(tx, params)
    step = pp.make_train_step(tx, params, donate=False)

    tokens = _tokens(8 * n_data)  # per data shard: 8 = M * mb(2)
    opt2, params2, m = step(opt_state, params, tokens)

    ref_loss = float(_reference_loss(pp, jax.tree.map(np.asarray, params),
                                     jnp.asarray(tokens)))
    np.testing.assert_allclose(float(m["loss"]), ref_loss, rtol=1e-5)

    # gradient parity: compare updated params against reference SGD step
    g_ref = jax.grad(
        lambda p: _reference_loss(pp, p, jnp.asarray(tokens))
    )(jax.tree.map(np.asarray, params))
    for (path, a), (_, g) in zip(
        jax.tree_util.tree_flatten_with_path(jax.tree.map(np.asarray, params2))[0],
        jax.tree_util.tree_flatten_with_path(g_ref)[0],
    ):
        orig = jax.tree_util.tree_flatten_with_path(
            jax.tree.map(np.asarray, params)
        )[0]
        expected = dict(orig)[path] - 0.1 * np.asarray(g)
        np.testing.assert_allclose(np.asarray(a), expected, rtol=1e-4,
                                   atol=1e-6, err_msg=str(path))


def test_pipeline_training_learns():
    mesh = build_mesh(MeshSpec(data=2, pipe=4, model=1))
    pp = PipelinedLM(mesh, CFG, num_microbatches=4)
    params = pp.init_params(jax.random.PRNGKey(1))
    tx = optax.adam(3e-3)
    opt_state = pp.init_opt_state(tx, params)
    step = pp.make_train_step(tx, params, donate=False)
    tokens = _tokens(16, seed=1)  # fixed batch -> memorize
    losses = []
    for _ in range(15):
        opt_state, params, m = step(opt_state, params, tokens)
        losses.append(float(m["loss"]))
    assert losses[-1] < losses[0] * 0.7, losses


@pytest.mark.parametrize("M,P", [(4, 4), (8, 4), (2, 4), (1, 2), (6, 2), (8, 8)])
def test_1f1b_schedule_invariants(M, P):
    from distributed_tensorflow_guide_tpu.parallel.pipeline import (
        _make_1f1b_schedule,
    )

    s = _make_1f1b_schedule(M, P)
    op, mb = s["op"], s["mb"]
    f_tick = {}
    b_tick = {}
    for t in range(s["T"]):
        for st in range(P):
            if op[t, st] == 1:
                f_tick[(st, mb[t, st])] = t
            elif op[t, st] == 2:
                b_tick[(st, mb[t, st])] = t
    # every microbatch forwarded and backwarded exactly once per stage
    assert set(f_tick) == {(st, m) for st in range(P) for m in range(M)}
    assert set(b_tick) == set(f_tick)
    inflight_max = 0
    for st in range(P):
        for m in range(M):
            # dataflow: activation arrives one tick after upstream F
            if st > 0:
                assert f_tick[(st, m)] > f_tick[(st - 1, m)]
            if st < P - 1:
                assert b_tick[(st, m)] > b_tick[(st + 1, m)]
            assert b_tick[(st, m)] > f_tick[(st, m)]
        inflight = max(
            sum(1 for m in range(M)
                if f_tick[(st, m)] <= t < b_tick[(st, m)])
            for t in range(s["T"])
        )
        inflight_max = max(inflight_max, inflight)
    # the 1F1B contract: in-flight bounded by pipeline depth, not M
    assert inflight_max <= min(P + 1, M), (inflight_max, M, P)
    assert s["R"] >= inflight_max


def _flop_ratio(cfg, mesh, pp):
    """Per-device traced matmul FLOPs of pp's step vs the unpipelined
    oracle on the same params — the shared protocol of the FLOP-discipline
    tests (tokens[:8] = one data shard's rows)."""
    from distributed_tensorflow_guide_tpu.utils.flop_accounting import (
        traced_matmul_flops,
    )

    params = pp.init_params(jax.random.PRNGKey(0))
    tx = optax.sgd(0.1)
    opt_state = pp.init_opt_state(tx, params)
    step = pp.make_train_step(tx, params, donate=False)
    tokens = jnp.zeros((16, cfg.max_len), jnp.int32)
    flops_pp = traced_matmul_flops(step, opt_state, params, tokens)

    def oracle(params, tokens):
        return jax.value_and_grad(
            lambda p: _reference_loss(pp, p, tokens)
        )(params)

    host_params = jax.tree.map(np.asarray, params)
    return flops_pp / traced_matmul_flops(oracle, host_params, tokens[:8])


def test_pipeline_flop_discipline():
    """The round-2 verdict's structural-waste finding, pinned as a test.

    Per-device traced matmul FLOPs of the GPipe step must stay close to the
    unpipelined oracle's. With this head-dominated config (vocab 2048, M=4,
    P=4) the pre-restructure code — embedder + full LM head applied EVERY
    tick on EVERY stage, discarded by masking — puts head+embed at
    (M+P-1)/M = 1.75x the oracle and totals ~1.6x; the restructured
    schedule (head once per microbatch on the owning stage, embed once on
    stage 0) totals ~0.8x (head/embed 1.0x, blocks (M+P-1)/(M*P) = 0.44x).
    The 1.1 threshold cleanly separates the two regimes — do not raise it
    without re-deriving these ratios. ``cost_analysis`` cannot see any of
    this (it counts scan bodies once); ``traced_matmul_flops`` multiplies
    trip counts.
    """
    cfg = TransformerConfig(
        vocab_size=2048, num_layers=4, num_heads=2, d_model=32, d_ff=64,
        max_len=16, causal=True, dtype=jnp.float32,
    )
    mesh = build_mesh(MeshSpec(data=2, pipe=4, model=1))
    pp = PipelinedLM(mesh, cfg, num_microbatches=4)
    ratio = _flop_ratio(cfg, mesh, pp)
    assert ratio < 1.1, (
        f"pipeline step does {ratio:.2f}x the oracle's matmul FLOPs per "
        "device — head/embed are being re-applied on non-owning stages"
    )
    assert ratio > 0.5, ratio  # sanity floor: blocks can't vanish


def test_auto_schedule_resolves_per_mesh():
    """schedule='auto' picks GPipe on a single-stage mesh (the 1F1B
    manual-VJP machinery is pure overhead with nothing in flight to cap —
    round-5 battery: GPipe 99.7k vs 1F1B 87.9k tok/s) and 1F1B at
    pipe >= 2 (the O(P) activation cap is the point of the schedule)."""
    mesh1 = build_mesh(MeshSpec(data=-1, pipe=1))
    pp1 = PipelinedLM(mesh1, CFG, num_microbatches=2, schedule="auto")
    assert pp1.schedule == "gpipe"
    mesh2 = build_mesh(MeshSpec(data=-1, pipe=2))
    pp2 = PipelinedLM(mesh2, CFG, num_microbatches=2, schedule="auto")
    assert pp2.schedule == "1f1b"
    # an explicit 1f1b at pipe=1 is honored (with a logged warning), never
    # silently rewritten
    pp3 = PipelinedLM(mesh1, CFG, num_microbatches=2, schedule="1f1b")
    assert pp3.schedule == "1f1b"


def test_unknown_schedule_rejected():
    mesh = build_mesh(MeshSpec(data=1, pipe=4, model=2))
    with pytest.raises(ValueError):
        PipelinedLM(mesh, CFG, num_microbatches=2, schedule="pipedream-2bw")


def test_layers_must_divide_stages():
    mesh = build_mesh(MeshSpec(data=1, pipe=8, model=1))
    cfg = TransformerConfig(num_layers=4)
    with pytest.raises(ValueError):
        PipelinedLM(mesh, cfg, num_microbatches=2)


def test_stage_params_actually_sharded():
    mesh = build_mesh(MeshSpec(data=1, pipe=4, model=2))
    pp = PipelinedLM(mesh, CFG, num_microbatches=2)
    params = pp.init_params(jax.random.PRNGKey(0))
    leaf = jax.tree.leaves(params["stages"])[0]
    assert leaf.shape[0] == 4
    assert leaf.addressable_shards[0].data.shape[0] == 1  # one stage per device
    # under tp the LM-head kernel is VOCAB-sharded over model (the
    # vocab-parallel cross-entropy's precondition: no device holds full V)
    k = params["head"]["lm_head"]["kernel"]
    assert k.shape == (CFG.d_model, CFG.vocab_size)
    assert k.addressable_shards[0].data.shape[1] == CFG.vocab_size // 2
    # ... and so is the token embedding (Megatron parallel embedding)
    w = params["embed"]["tok_emb"]["embedding"]
    assert w.addressable_shards[0].data.shape[0] == CFG.vocab_size // 2


@pytest.mark.parametrize("schedule", ["gpipe", "1f1b"])
@pytest.mark.parametrize("n_pipe,v,M,tp", [(2, 2, 4, 1), (4, 2, 8, 1),
                                           (2, 4, 4, 1), (2, 2, 4, 2)])
def test_interleaved_pipeline_matches_unpipelined(n_pipe, v, M, tp, schedule):
    """Interleaved pipelining (virtual chunks) is an execution schedule:
    loss and gradients must equal the unpipelined oracle's, like every
    other schedule — at several (stages, chunks, microbatches) shapes,
    under BOTH the autodiff gpipe drain and the manual-VJP combined
    interleaved-1F1B (Megatron production) schedule. The tp=2 case is the
    full 3D program: data(2) x pipe(2) x model(2) with v=2 virtual chunks —
    TP-sharded stages inside an interleaved pipeline under data
    parallelism."""
    cfg = TransformerConfig(
        vocab_size=64, num_layers=8, num_heads=2, d_model=32, d_ff=64,
        max_len=16, causal=True, dtype=jnp.float32,
    )
    mesh = build_mesh(MeshSpec(data=-1, pipe=n_pipe, model=tp))
    n_data = mesh.shape["data"]
    pp = PipelinedLM(mesh, cfg, num_microbatches=M, schedule=schedule,
                     virtual_chunks=v)
    params = pp.init_params(jax.random.PRNGKey(0))
    tx = optax.sgd(0.1)
    opt_state = pp.init_opt_state(tx, params)
    step = pp.make_train_step(tx, params, donate=False)

    rng = np.random.RandomState(0)
    tokens = rng.randint(0, cfg.vocab_size,
                         (M * 2 * n_data, cfg.max_len)).astype(np.int32)
    opt2, params2, m = step(opt_state, params, tokens)

    host_params = jax.tree.map(np.asarray, params)
    ref_loss = float(_reference_loss(pp, host_params, jnp.asarray(tokens)))
    np.testing.assert_allclose(float(m["loss"]), ref_loss, rtol=1e-5)

    g_ref = jax.grad(
        lambda p: _reference_loss(pp, p, jnp.asarray(tokens))
    )(host_params)
    orig = dict(jax.tree_util.tree_flatten_with_path(host_params)[0])
    for (path, a), (_, g) in zip(
        jax.tree_util.tree_flatten_with_path(
            jax.tree.map(np.asarray, params2))[0],
        jax.tree_util.tree_flatten_with_path(g_ref)[0],
        strict=True,
    ):
        expected = orig[path] - 0.1 * np.asarray(g)
        np.testing.assert_allclose(np.asarray(a), expected, rtol=1e-4,
                                   atol=1e-6, err_msg=str(path))


@pytest.mark.parametrize("M,P,v", [(4, 2, 2), (8, 4, 2), (4, 4, 4),
                                   (8, 4, 1), (8, 2, 4), (1, 2, 2)])
def test_interleaved_schedule_invariants(M, P, v):
    from distributed_tensorflow_guide_tpu.parallel.pipeline import (
        _make_interleaved_schedule,
    )

    s = _make_interleaved_schedule(M, P, v)
    D = v * P
    done = s["done"]
    # every chunk-stage runs every microbatch exactly once, in dependency
    # and per-chunk FIFO order
    for k in range(D):
        for m in range(M):
            assert done[k][m] >= 0
            if k:
                assert done[k][m] > done[k - 1][m]
            if m:
                assert done[k][m] > done[k][m - 1]
    # one op per device per tick (the tables are per-device by construction)
    # and the bubble shrinks: T counts 1/v-stage ticks, so the equivalent
    # full-stage time is T/v, vs GPipe's M + P - 1. v=1 must degenerate
    # exactly.
    T = s["T"]
    assert T >= M * v  # device 0 alone needs M*v ticks
    if v == 1:
        assert T == M + P - 1
    elif M == 1:
        # single microbatch: serial traversal of all D chunk-stages, no
        # bubble to amortize — interleaving neither helps nor hurts
        assert T / v == M + P - 1, (T, v, M, P)
    else:
        assert T / v < M + P - 1, (T, v, M, P)


@pytest.mark.parametrize("M,P,v", [(4, 2, 2), (8, 4, 2), (8, 2, 4),
                                   (16, 4, 2), (32, 4, 2)])
def test_interleaved_1f1b_schedule_invariants(M, P, v):
    """The combined schedule must deliver BOTH contracts at once:
    dependency-correct dataflow, an in-flight activation cap that depends
    on (P, v) but NOT on M (the 1F1B memory contract), and a bubble no
    worse than plain 1F1B's (P-1)/(M+P-1) (the interleaving contract)."""
    from distributed_tensorflow_guide_tpu.parallel.pipeline import (
        _make_interleaved_1f1b_schedule,
    )

    s = _make_interleaved_1f1b_schedule(M, P, v)
    D = v * P
    f, b = s["f_done"], s["b_done"]
    for k in range(D):
        for m in range(M):
            assert f[k][m] >= 0 and b[k][m] > f[k][m]
            if k:
                # hand-off is one ppermute tick: strict ordering both ways
                assert f[k][m] > f[k - 1][m]
                assert b[k - 1][m] > b[k][m]
    # one op per device per tick is structural (tables are (T, P))
    # memory contract: warmup cap 2(P-1) + (v-1)P + 1, independent of M
    cap = 2 * (P - 1) + (v - 1) * P + 1
    assert s["max_inflight"] <= cap, (s["max_inflight"], cap)
    assert s["R"] < 2 * v * M or v * M <= cap  # ring stays well under full
    # bubble contract: an interleaved tick costs a 1/v stage, so the
    # equivalent full-stage time is T/v; it must beat plain 1F1B's total
    # (both schedules' tables model fwd and bwd ticks as equal cost)
    from distributed_tensorflow_guide_tpu.parallel.pipeline import (
        _make_1f1b_schedule,
    )

    T_plain = _make_1f1b_schedule(M, P)["T"]
    assert s["T"] / v < T_plain, (s["T"], v, T_plain, M, P)


def test_schedule_generators_judged_scale_and_cached():
    """Round-4 verdict weak 3: nothing exercised v5e-16-scale tables
    (P=16, M=64, v=2 — where config 5's judged shape lives) or caching
    across retraces. Generates the judged-scale table under a time budget,
    re-checks the in-flight cap and slot safety there, and pins the
    lru_cache contract (same key -> same frozen object, no regeneration)."""
    import time as _time

    from distributed_tensorflow_guide_tpu.parallel.pipeline import (
        _make_1f1b_schedule,
        _make_interleaved_1f1b_schedule,
        _make_interleaved_schedule,
    )

    M, P, v = 64, 16, 2
    t0 = _time.perf_counter()
    s = _make_interleaved_1f1b_schedule(M, P, v)
    s1 = _make_1f1b_schedule(M, P)
    s2 = _make_interleaved_schedule(M, P, v)
    gen_time = _time.perf_counter() - t0
    # trace-time budget: the greedy simulations are O(T*P); at judged scale
    # they must stay a negligible slice of a ~30s XLA compile
    assert gen_time < 10.0, f"schedule generation took {gen_time:.1f}s"
    # 1F1B memory contract at scale: in-flight cap depends on (P, v), not M
    cap = 2 * (P - 1) + (v - 1) * P + 1
    assert s["max_inflight"] <= cap, (s["max_inflight"], cap)
    # ring depth: slot-reuse distance can reach ~2x the in-flight cap, but
    # must track the (P, v)-cap, NOT the v*M == 128 microbatch total
    assert s["R"] <= 2 * cap + 1, (s["R"], cap)
    assert s1["R"] <= P + 1  # plain 1F1B: depth-bounded, not M == 64
    # slot safety at scale: every store lands in a slot whose previous
    # occupant was already consumed (the generators self-check and raise,
    # so reaching here with finite T is the assertion)
    assert s["T"] > 0 and s1["T"] > 0 and s2["T"] > 0
    # cache contract: a retrace's regeneration is a dict lookup returning
    # the SAME object with read-only tables
    assert _make_interleaved_1f1b_schedule(M, P, v) is s
    assert _make_1f1b_schedule(M, P) is s1
    assert _make_interleaved_schedule(M, P, v) is s2
    assert s["op"].flags.writeable is False
    with pytest.raises(ValueError):
        s1["op"][0, 0] = 0


def test_interleaved_1f1b_requires_divisible_microbatches():
    mesh = build_mesh(MeshSpec(data=1, pipe=4, model=2))
    cfg = TransformerConfig(
        vocab_size=64, num_layers=8, num_heads=2, d_model=32, d_ff=64,
        max_len=16, causal=True, dtype=jnp.float32,
    )
    with pytest.raises(ValueError, match="divisible"):
        PipelinedLM(mesh, cfg, num_microbatches=6, schedule="1f1b",
                    virtual_chunks=2)


def test_interleaved_flop_discipline():
    """Interleaved GPipe keeps the head/embed FLOP contract: owner-only,
    once per microbatch — the 1.1x bound fails if either is re-applied per
    tick or per stage. NOTE what this does NOT guard: traced_matmul_flops
    models lax.cond as max-of-branches, so the runtime-free idle ticks are
    still CHARGED here (a regression to compute-and-mask idle ticks is
    invisible to this counter; gradient parity and the schedule-invariant
    tests are the guards for that path's correctness)."""
    cfg = TransformerConfig(
        vocab_size=2048, num_layers=8, num_heads=2, d_model=32, d_ff=64,
        max_len=16, causal=True, dtype=jnp.float32,
    )
    mesh = build_mesh(MeshSpec(data=2, pipe=2, model=2))
    pp = PipelinedLM(mesh, cfg, num_microbatches=4, virtual_chunks=2)
    ratio = _flop_ratio(cfg, mesh, pp)
    assert ratio < 1.1, (
        f"interleaved step does {ratio:.2f}x the oracle's matmul FLOPs per "
        "device — non-owner head/embed are burning compute"
    )
    # Sanity floor: this mesh has model=2, so the vocab-parallel head puts
    # only V/tp of the head matmul on each device (~0.37 with this
    # head-dominated config, vs ~0.65 when the head was replicated). A
    # ratio below this floor would mean block compute itself went missing.
    assert ratio > 0.3, ratio


@pytest.mark.parametrize("schedule,n_pipe,v,tp",
                         [("gpipe", 4, 1, 1),      # plain GPipe (autodiff)
                          ("1f1b", 4, 1, 1),       # plain 1F1B (manual VJP)
                          ("1f1b", 2, 2, 2)])      # interleaved-1F1B + TP:
                                                   # vocab-parallel fused CE
def test_pipeline_fused_ce_gradient_identity(schedule, n_pipe, v, tp):
    """The round-8 acceptance pin: with ``fused_ce=True`` (chunked fused
    cross-entropy, chunk 16 < V so the loop really chunks) every schedule
    still matches the naive unpipelined oracle — loss AND grads. All
    schedules dispatch through the one ``_mb_loss``, so this is the
    gradient-identity contract surviving the loss-path swap; the three
    cases cover the autodiff drain, the manual-VJP tick loop, and the
    combined interleaved schedule — the last under tp=2, where fused CE
    subsumes the vocab-parallel loss."""
    mesh = build_mesh(MeshSpec(data=-1, pipe=n_pipe, model=tp))
    n_data = mesh.shape["data"]
    M = 4
    pp = PipelinedLM(mesh, CFG, num_microbatches=M, schedule=schedule,
                     virtual_chunks=v, fused_ce=True, ce_chunk=16)
    assert pp.fused_ce is True
    params = pp.init_params(jax.random.PRNGKey(0))
    tx = optax.sgd(0.1)
    opt_state = pp.init_opt_state(tx, params)
    step = pp.make_train_step(tx, params, donate=False)

    rng = np.random.RandomState(0)
    tokens = rng.randint(0, CFG.vocab_size,
                         (M * 2 * n_data, CFG.max_len)).astype(np.int32)
    opt2, params2, m = step(opt_state, params, tokens)

    host_params = jax.tree.map(np.asarray, params)
    ref_loss = float(_reference_loss(pp, host_params, jnp.asarray(tokens)))
    np.testing.assert_allclose(float(m["loss"]), ref_loss, rtol=1e-5)

    g_ref = jax.grad(
        lambda p: _reference_loss(pp, p, jnp.asarray(tokens))
    )(host_params)
    orig = dict(jax.tree_util.tree_flatten_with_path(host_params)[0])
    for (path, a), (_, g) in zip(
        jax.tree_util.tree_flatten_with_path(
            jax.tree.map(np.asarray, params2))[0],
        jax.tree_util.tree_flatten_with_path(g_ref)[0],
        strict=True,
    ):
        expected = orig[path] - 0.1 * np.asarray(g)
        np.testing.assert_allclose(np.asarray(a), expected, rtol=1e-4,
                                   atol=1e-6, err_msg=str(path))


@pytest.mark.parametrize("schedule,n_pipe,v", [("gpipe", 4, 1),
                                                ("gpipe", 2, 2),
                                                ("1f1b", 4, 1)])
def test_remat_parity_across_schedules(schedule, n_pipe, v):
    """cfg.remat on any schedule is an execution-plan change (and a no-op
    under 1F1B, which already recomputes): gradient parity with the
    non-remat run must hold on every path."""
    import dataclasses

    mesh = build_mesh(MeshSpec(data=-1, pipe=n_pipe))
    n_data = mesh.shape["data"]
    tokens = _tokens(4 * 2 * n_data)

    def one_step(remat):
        cfg = dataclasses.replace(CFG, remat=remat)
        pp = PipelinedLM(mesh, cfg, num_microbatches=4, schedule=schedule,
                         virtual_chunks=v)
        params = pp.init_params(jax.random.PRNGKey(0))
        tx = optax.sgd(0.1)
        opt_state = pp.init_opt_state(tx, params)
        step = pp.make_train_step(tx, params, donate=False)
        _, params2, m = step(opt_state, params, tokens)
        return float(m["loss"]), jax.tree.map(np.asarray, params2)

    l0, p0 = one_step(False)
    l1, p1 = one_step(True)
    np.testing.assert_allclose(l0, l1, rtol=1e-6)
    for a, b in zip(jax.tree.leaves(p0), jax.tree.leaves(p1), strict=True):
        np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-7)


def test_steps_per_call_trajectory_parity():
    """K optimizer steps inside one compiled call (lax.scan around the whole
    pipeline schedule) must land on exactly the trajectory of K separate
    calls — both the synthetic re-use mode and the stacked real-data mode."""
    mesh = build_mesh(MeshSpec(data=2, pipe=4, model=1))
    pp = PipelinedLM(mesh, CFG, num_microbatches=4)
    tx = optax.adam(3e-3)
    K = 3

    def run(steps_per_call, stacked, tokens, n_calls):
        params = pp.init_params(jax.random.PRNGKey(1))
        opt_state = pp.init_opt_state(tx, params)
        step = pp.make_train_step(tx, params, donate=False,
                                  steps_per_call=steps_per_call,
                                  stacked_batch=stacked)
        for i in range(n_calls):
            b = tokens[i] if not stacked and tokens.ndim == 3 else tokens
            opt_state, params, m = step(opt_state, params, b)
        return params, float(m["loss"])

    # synthetic mode: same batch every inner step
    flat = _tokens(16, seed=1)
    p_multi, _ = run(K, False, flat, 1)
    p_loop, _ = run(1, False, np.stack([flat] * K), K)
    for a, b in zip(jax.tree.leaves(p_multi), jax.tree.leaves(p_loop)):
        np.testing.assert_allclose(a, b, rtol=1e-6, atol=1e-7)

    # stacked mode: one batch slice per inner step
    stack = np.stack([_tokens(16, seed=s) for s in range(K)])
    p_multi, _ = run(K, True, stack, 1)
    p_loop, _ = run(1, False, stack, K)
    for a, b in zip(jax.tree.leaves(p_multi), jax.tree.leaves(p_loop)):
        np.testing.assert_allclose(a, b, rtol=1e-6, atol=1e-7)


def test_steps_per_call_validation():
    mesh = build_mesh(MeshSpec(data=-1, pipe=2, model=1))
    cfg = CFG
    pp = PipelinedLM(mesh, cfg, num_microbatches=2)
    tx = optax.adam(1e-3)
    params = pp.init_params(jax.random.PRNGKey(0))
    with pytest.raises(ValueError, match="steps_per_call"):
        pp.make_train_step(tx, params, steps_per_call=0)
    with pytest.raises(ValueError, match="stacked_batch"):
        pp.make_train_step(tx, params, stacked_batch=True)
    step = pp.make_train_step(tx, params, donate=False, steps_per_call=2,
                              stacked_batch=True)
    opt_state = pp.init_opt_state(tx, params)
    bad = np.stack([_tokens(8)] * 3)  # leading axis 3 != steps_per_call 2
    with pytest.raises(ValueError, match="leading axis"):
        step(opt_state, params, bad)


@pytest.mark.parametrize("n_pipe,v", [(4, 1), (2, 2)])
def test_to_serving_params_logits_parity(n_pipe, v):
    """A pipeline-trained param tree converted to the flat Transformer
    layout must produce the same LM loss as the pipeline computes — the
    train-with-PP / serve-with-generation contract (incl. inverting the
    interleaved chunk permutation)."""
    from distributed_tensorflow_guide_tpu.models.transformer import (
        Transformer,
        make_lm_loss_fn,
    )

    mesh = build_mesh(MeshSpec(data=8 // n_pipe, pipe=n_pipe, model=1))
    pp = PipelinedLM(mesh, CFG, num_microbatches=4,
                     schedule="gpipe" if v > 1 else "1f1b",
                     virtual_chunks=v)
    params = pp.init_params(jax.random.PRNGKey(2))
    tx = optax.adam(3e-3)
    opt_state = pp.init_opt_state(tx, params)
    step = pp.make_train_step(tx, params, donate=False)
    tokens = _tokens(16, seed=4)
    # one optimizer step so the converted tree is trained, not just inited
    opt_state, params, m = step(opt_state, params, tokens)

    serving = pp.to_serving_params(jax.device_get(params))
    loss_fn = make_lm_loss_fn(Transformer(CFG))
    loss, _ = loss_fn(serving, {"tokens": _tokens(16, seed=4)})

    # oracle: the pipeline's own loss on the SAME (post-step) params
    _, _, m2 = step(opt_state, params, tokens)
    # m2's loss is post-second-step? No: metrics are computed on the params
    # passed in, before the update — exactly the converted tree.
    np.testing.assert_allclose(float(loss), float(m2["loss"]),
                               rtol=1e-5, atol=1e-6)

    # generation runs on the converted tree (end of the contract)
    from distributed_tensorflow_guide_tpu.models.generation import (
        make_generate_fn,
    )

    gen = make_generate_fn(CFG, max_new_tokens=3, temperature=0.0)
    out = np.asarray(gen(serving, _tokens(2, seed=5)[:, :8],
                         jax.random.PRNGKey(0)))
    assert out.shape == (2, 11)


def test_pipeline_eval_step_matches_oracle():
    """Forward-only eval loss == the unpipelined oracle's loss, and the
    Evaluator drives it over a finite stream."""
    from distributed_tensorflow_guide_tpu.train.evaluation import Evaluator

    mesh = build_mesh(MeshSpec(data=2, pipe=4, model=1))
    pp = PipelinedLM(mesh, CFG, num_microbatches=4)
    params = pp.init_params(jax.random.PRNGKey(0))
    ev_step = pp.make_eval_step()
    tokens = _tokens(16, seed=9)
    got = ev_step(params, tokens)
    want = float(_reference_loss(pp, jax.tree.map(np.asarray, params),
                                 jnp.asarray(tokens)))
    np.testing.assert_allclose(float(got["loss"]), want, rtol=1e-5)
    np.testing.assert_allclose(float(got["perplexity"]), np.exp(want),
                               rtol=1e-4)

    ev = Evaluator(lambda p, b: ev_step(p, b),
                   lambda: (_tokens(16, seed=s) for s in (1, 2)))
    out = ev.run(params)
    assert out["eval_batches"] == 2.0 and out["loss"] > 0


def test_tp_steps_per_call_trajectory_parity():
    """TensorParallel K-steps-per-dispatch == K separate calls, both modes."""
    import optax as _optax
    from flax.training import train_state as _ts

    from distributed_tensorflow_guide_tpu.models.transformer import (
        Transformer,
        make_cls_loss_fn,
    )
    from distributed_tensorflow_guide_tpu.parallel.tensor import TensorParallel

    cfg = TransformerConfig(
        vocab_size=64, num_layers=2, num_heads=4, d_model=32, d_ff=64,
        max_len=16, causal=False, num_classes=2, dtype=jnp.float32)
    mesh = build_mesh(MeshSpec(data=2, model=4))
    model = Transformer(cfg)
    tp = TensorParallel(mesh)
    loss_fn = make_cls_loss_fn(model)
    K = 3

    def fresh_state():
        params, shardings = tp.init_params(
            model, jax.random.PRNGKey(0),
            jnp.zeros((1, cfg.max_len), jnp.int32))
        st = _ts.TrainState.create(apply_fn=model.apply, params=params,
                                   tx=_optax.adam(1e-2))
        sh = tp.state_shardings(st, shardings)
        return jax.device_put(st, sh), sh

    rng = np.random.RandomState(0)
    def batch(seed):
        r = np.random.RandomState(seed)
        t = r.randint(0, 64, (8, cfg.max_len)).astype(np.int32)
        return {"tokens": t, "label": (t[:, 0] % 2).astype(np.int32)}

    stack = jax.tree.map(lambda *xs: np.stack(xs),
                         *[batch(s) for s in range(K)])

    st, sh = fresh_state()
    step1 = tp.make_train_step(loss_fn, sh, donate=False)
    for s in range(K):
        st, _ = step1(st, batch(s))
    want = jax.device_get(st.params)

    st2, sh2 = fresh_state()
    stepK = tp.make_train_step(loss_fn, sh2, donate=False,
                               steps_per_call=K, stacked_batch=True)
    st2, _ = stepK(st2, stack)
    got = jax.device_get(st2.params)
    for a, b in zip(jax.tree.leaves(got), jax.tree.leaves(want)):
        np.testing.assert_allclose(a, b, rtol=1e-6, atol=1e-7)
