"""Config-5 coverage: GPipe pipeline parallelism over the pipe mesh axis.

The load-bearing test is parity: the pipelined step must produce the SAME
loss and gradients as an unpipelined run of the identical stacked-layer
model (pipelining is an execution schedule, not a different algorithm)."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax import lax

from distributed_tensorflow_guide_tpu.core.mesh import MeshSpec, build_mesh
from distributed_tensorflow_guide_tpu.models.transformer import (
    Block,
    TransformerConfig,
)
from distributed_tensorflow_guide_tpu.parallel.pipeline import PipelinedLM

CFG = TransformerConfig(
    vocab_size=64, num_layers=4, num_heads=2, d_model=32, d_ff=64,
    max_len=16, causal=True, dtype=jnp.float32,
)


def _tokens(batch, seed=0):
    rng = np.random.RandomState(seed)
    return rng.randint(0, CFG.vocab_size, (batch, CFG.max_len)).astype(np.int32)


def _reference_loss(pp, params, tokens):
    """Unpipelined forward with the same stacked params."""
    x = pp.embedder.apply({"params": params["embed"]}, tokens)
    flat = jax.tree.map(
        lambda s: s.reshape(-1, *s.shape[2:]), params["stages"]
    )

    def body(h, layer_params):
        return pp.block.apply({"params": layer_params}, h), None

    x, _ = lax.scan(body, x, flat)
    logits = pp.head.apply({"params": params["head"]}, x)
    logp = jax.nn.log_softmax(logits[:, :-1])
    ll = jnp.take_along_axis(logp, tokens[:, 1:][..., None], axis=-1)[..., 0]
    return -jnp.mean(ll)


@pytest.mark.parametrize("n_pipe,n_data", [(4, 1), (2, 2)])
def test_pipeline_matches_unpipelined(n_pipe, n_data):
    mesh = build_mesh(MeshSpec(data=n_data, pipe=n_pipe, model=8 // (n_pipe * n_data)))
    M = 4  # microbatches
    pp = PipelinedLM(mesh, CFG, num_microbatches=M)
    params = pp.init_params(jax.random.PRNGKey(0))
    tx = optax.sgd(0.1)
    opt_state = pp.init_opt_state(tx, params)
    step = pp.make_train_step(tx, params, donate=False)

    tokens = _tokens(8 * n_data)  # per data shard: 8 = M * mb(2)
    opt2, params2, m = step(opt_state, params, tokens)

    ref_loss = float(_reference_loss(pp, jax.tree.map(np.asarray, params),
                                     jnp.asarray(tokens)))
    np.testing.assert_allclose(float(m["loss"]), ref_loss, rtol=1e-5)

    # gradient parity: compare updated params against reference SGD step
    g_ref = jax.grad(
        lambda p: _reference_loss(pp, p, jnp.asarray(tokens))
    )(jax.tree.map(np.asarray, params))
    for (path, a), (_, g) in zip(
        jax.tree_util.tree_flatten_with_path(jax.tree.map(np.asarray, params2))[0],
        jax.tree_util.tree_flatten_with_path(g_ref)[0],
    ):
        orig = jax.tree_util.tree_flatten_with_path(
            jax.tree.map(np.asarray, params)
        )[0]
        expected = dict(orig)[path] - 0.1 * np.asarray(g)
        np.testing.assert_allclose(np.asarray(a), expected, rtol=1e-4,
                                   atol=1e-6, err_msg=str(path))


def test_pipeline_training_learns():
    mesh = build_mesh(MeshSpec(data=2, pipe=4, model=1))
    pp = PipelinedLM(mesh, CFG, num_microbatches=4)
    params = pp.init_params(jax.random.PRNGKey(1))
    tx = optax.adam(3e-3)
    opt_state = pp.init_opt_state(tx, params)
    step = pp.make_train_step(tx, params, donate=False)
    tokens = _tokens(16, seed=1)  # fixed batch -> memorize
    losses = []
    for _ in range(15):
        opt_state, params, m = step(opt_state, params, tokens)
        losses.append(float(m["loss"]))
    assert losses[-1] < losses[0] * 0.7, losses


def test_layers_must_divide_stages():
    mesh = build_mesh(MeshSpec(data=1, pipe=8, model=1))
    cfg = TransformerConfig(num_layers=4)
    with pytest.raises(ValueError):
        PipelinedLM(mesh, cfg, num_microbatches=2)


def test_stage_params_actually_sharded():
    mesh = build_mesh(MeshSpec(data=1, pipe=4, model=2))
    pp = PipelinedLM(mesh, CFG, num_microbatches=2)
    params = pp.init_params(jax.random.PRNGKey(0))
    leaf = jax.tree.leaves(params["stages"])[0]
    assert leaf.shape[0] == 4
    assert leaf.addressable_shards[0].data.shape[0] == 1  # one stage per device
