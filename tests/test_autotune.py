"""Autotune layer (ops/autotune.py): table roundtrip determinism, the CPU
defaults-only hermeticity contract, numerical parity of every swept block
candidate against the XLA reference, and the plumbing that carries tuned
blocks from the table to the flash/carry call sites.

The sweep itself is exercised with an INJECTED measure function (platform
forced to "tpu", table redirected to a tmp path): the mechanism — candidate
enumeration, winner selection, persistence, no-re-sweep — is what CI can
pin; real timings only mean something on chip (bench_flash_kernel --tune).
"""

import json
import os
from pathlib import Path

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_tensorflow_guide_tpu.ops import autotune
from distributed_tensorflow_guide_tpu.ops import flash_attention as F
from distributed_tensorflow_guide_tpu.ops.attention import dense_attention
from distributed_tensorflow_guide_tpu.ops.flash_attention import (
    flash_attention,
)


@pytest.fixture(autouse=True)
def _isolated_table(isolated_autotune_table):
    """Shared isolation (tests/conftest.py): every test gets an empty
    in-memory table and a tmp table file — nothing leaks between tests or
    to the user's cache."""
    yield


SHAPE = dict(b=1, h=1, s=256, d=64)


def _qkv(s=256, d=64, b=1, h=2, seed=0):
    r = np.random.RandomState(seed)

    def mk():
        return jnp.asarray(r.randn(b, s, h, d), jnp.float32)

    return mk(), mk(), mk()


# ---- table mechanics --------------------------------------------------------


def test_roundtrip_determinism_no_resweep():
    """Same key -> same blocks, sweep runs ONCE; the persisted table
    survives a simulated process restart (in-memory state dropped)."""
    calls = []

    def measure(kernel, blocks):
        calls.append(blocks)
        return 1.0 / (blocks[0] * blocks[1])  # favors the largest blocks

    kw = dict(**SHAPE, dtype=jnp.float32, platform="tpu")
    first = autotune.ensure_tuned("flash_fwd", measure=measure, **kw)
    n_swept = len(calls)
    cands = autotune.candidate_blocks("flash_fwd", s=SHAPE["s"],
                                      d=SHAPE["d"], dtype=jnp.float32)
    assert n_swept == len(cands) and first == (256, 256)

    again = autotune.ensure_tuned("flash_fwd", measure=measure, **kw)
    assert again == first and len(calls) == n_swept  # no re-sweep

    autotune.reset()  # "restart": reload from the persisted file
    reloaded = autotune.ensure_tuned("flash_fwd", measure=measure, **kw)
    assert reloaded == first and len(calls) == n_swept

    # the batch/head-generic entry serves nearby shapes without a sweep
    assert autotune.blocks_for("flash_fwd", b=4, h=8, s=256, d=64,
                               dtype=jnp.float32, platform="tpu") == first
    # ...but a different seq/dtype misses back to the tested default
    assert autotune.blocks_for("flash_fwd", b=1, h=1, s=512, d=64,
                               dtype=jnp.float32,
                               platform="tpu") == autotune.DEFAULT_BLOCKS


def test_cpu_is_defaults_only_no_table_io():
    """The tier-1 hermeticity contract: under the CPU platform the table
    file is neither read (a stray host table must not change what CI
    traces) nor written, and sweeps are refused outright."""
    path = Path(os.environ["DTG_AUTOTUNE_TABLE"])
    seeded = {autotune._key("flash_fwd", 0, 0, 256, 64, "float32", True, "cpu"):
              {"blk_q": 64, "blk_k": 64}}
    path.write_text(json.dumps(seeded))

    # default platform resolves to the test backend (cpu): file ignored
    assert autotune.blocks_for(
        "flash_fwd", **SHAPE, dtype=jnp.float32) == autotune.DEFAULT_BLOCKS
    with pytest.raises(RuntimeError, match="defaults-only"):
        autotune.ensure_tuned("flash_fwd", **SHAPE, dtype=jnp.float32,
                              measure=lambda *a: 0.0)
    with pytest.raises(RuntimeError, match="defaults-only"):
        autotune.record("flash_fwd", **SHAPE, dtype=jnp.float32,
                        blocks=(64, 64))
    assert json.loads(path.read_text()) == seeded  # file untouched


def test_stale_or_invalid_entries_fall_back_to_default():
    # 96 is a sublane multiple but does not divide 256 — a stale entry
    # (e.g. hand-edited table or a shape change) must not reach the kernel
    autotune._mem[autotune._key("flash_fwd", 0, 0, 256, 64, "float32",
                                True, "tpu")] = {"blk_q": 96, "blk_k": 96}
    assert autotune.blocks_for(
        "flash_fwd", **SHAPE, dtype=jnp.float32,
        platform="tpu") == autotune.DEFAULT_BLOCKS
    with pytest.raises(ValueError, match="invalid"):
        autotune.record("flash_fwd", **SHAPE, dtype=jnp.float32,
                        blocks=(96, 96), platform="tpu")


def test_candidates_all_valid_and_within_vmem_budget():
    for kern in autotune.KERNELS:
        for s in (128, 256, 1024):
            cands = autotune.candidate_blocks(kern, s=s, d=64,
                                              dtype=jnp.bfloat16)
            assert cands, (kern, s)
            for bq, bk in cands:
                assert s % bq == 0 and s % bk == 0 and bq % 8 == 0
                assert autotune.kernel_vmem_bytes(
                    kern, bq, bk, 128, jnp.bfloat16
                ) <= autotune.VMEM_BUDGET_BYTES


def test_roofline_models_sanity():
    # non-causal: every block pair is live -> closed-form FLOPs
    kw = dict(b=2, h=3, s=256, d=64, blocks=(128, 128))
    f = autotune.kernel_flops("flash_fwd", causal=False, **kw)
    assert f == 2.0 * 2 * 128 * 128 * 128 * 4 * 2 * 3  # 2 passes, 4 live
    # causal at 2x2 blocks: 3 of 4 live (one strictly above the diagonal)
    assert autotune.kernel_flops(
        "flash_fwd", causal=True, **kw) == f * 3 / 4
    # dkv does 4 MXU passes per block to fwd's 2
    assert autotune.kernel_flops("flash_dkv", causal=False, **kw) == 2 * f
    # byte model: block-independent (minimal algorithmic traffic), and
    # bf16 IO halves the head-dim tensors but not the f32 stats
    b32 = autotune.kernel_hbm_bytes("flash_fwd", b=1, h=1, s=256, d=64,
                                    dtype=jnp.float32)
    b16 = autotune.kernel_hbm_bytes("flash_fwd", b=1, h=1, s=256, d=64,
                                    dtype=jnp.bfloat16)
    t, lane = 256 * 128, 256 * 128
    assert b32 == 4 * t * 4 + lane * 4
    assert b16 == 4 * t * 2 + lane * 4


# ---- numerical parity of the sweep space ------------------------------------


def test_every_swept_block_pair_matches_dense_forward():
    """Every candidate the sweep may ever pick must be numerically exact —
    the sweep optimizes time, never correctness."""
    q, k, v = _qkv()
    ref = dense_attention(q, k, v, causal=True)
    cands = autotune.candidate_blocks("flash_fwd", s=256, d=64,
                                      dtype=jnp.float32)
    assert (64, 64) in cands and (256, 256) in cands
    for bq, bk in cands:
        out = flash_attention(q, k, v, causal=True, blk_q=bq, blk_k=bk)
        np.testing.assert_allclose(out, ref, atol=1e-4, rtol=1e-4,
                                   err_msg=f"blocks ({bq}, {bk})")


@pytest.mark.parametrize("blocks", [(64, 64), (64, 256), (256, 64),
                                    (256, 256)])
def test_swept_blocks_gradient_parity(blocks):
    """Backward kernels at non-default blocks (incl. asymmetric pairs —
    the dq/dkv grids transpose) against the dense-attention gradients."""
    q, k, v = _qkv(h=1)

    def loss(fn, **kw):
        return lambda q, k, v: jnp.sum(fn(q, k, v, causal=True, **kw) ** 2)

    g_flash = jax.grad(
        loss(flash_attention, blk_q=blocks[0], blk_k=blocks[1]),
        argnums=(0, 1, 2))(q, k, v)
    g_dense = jax.grad(loss(dense_attention), argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_flash, g_dense):
        scale = float(jnp.max(jnp.abs(b))) + 1e-6
        np.testing.assert_allclose(a / scale, b / scale, atol=1e-3)


# ---- call-site plumbing -----------------------------------------------------


def test_flash_attention_resolves_all_three_kernels_from_table(monkeypatch):
    """With no explicit blocks, flash_attention consults the table once per
    kernel (fwd, dq, dkv) — the no-hardcoded-blocks contract."""
    seen = []
    real = autotune.blocks_for

    def spy(kernel, **kw):
        out = real(kernel, **kw)
        seen.append(kernel)
        return out

    monkeypatch.setattr(autotune, "blocks_for", spy)
    q, k, v = _qkv()
    flash_attention(q, k, v, causal=True)
    assert {"flash_fwd", "flash_dq", "flash_dkv"} <= set(seen)


def test_recorded_blocks_change_resolution_and_stay_exact():
    """An in-memory table entry redirects the default resolution (here on
    the cpu platform key, which only tests can seed — the file path is
    closed by the hermeticity contract) and the result stays exact."""
    for kern in ("flash_fwd", "flash_dq", "flash_dkv"):
        autotune._mem[autotune._key(kern, 0, 0, 256, 64, "float32",
                                    True, "cpu")] = {"blk_q": 64, "blk_k": 64}
    assert autotune.blocks_for("flash_fwd", b=1, h=2, s=256, d=64,
                               dtype=jnp.float32) == (64, 64)
    q, k, v = _qkv()
    out = flash_attention(q, k, v, causal=True)  # resolves 64x64
    ref = dense_attention(q, k, v, causal=True)
    np.testing.assert_allclose(out, ref, atol=1e-4, rtol=1e-4)


def test_carry_blocks_consults_table():
    autotune._mem[autotune._key("carry_step", 0, 0, 256, 64, "float32",
                                True, "cpu")] = {"blk_q": 64, "blk_k": 128}
    assert F.carry_blocks(2, 2, 256, 64, jnp.float32) == (64, 128)
    # and the default fallback holds on a miss
    assert F.carry_blocks(2, 2, 512, 64,
                          jnp.float32) == autotune.DEFAULT_BLOCKS


def test_kernel_runners_execute_and_agree_with_reference():
    """The sweep/microbench runners drive the REAL kernels: the forward
    runner's normalized output must match dense attention on the same
    operands (guards the runner harness itself against drift)."""
    kw = dict(b=1, h=1, s=128, d=64, dtype=jnp.float32, causal=True)
    fn = autotune.make_kernel_runner("flash_fwd", (64, 64), **kw)
    out, lse = fn()
    # rebuild the runner's operands (same seed path) for the oracle
    keys = jax.random.split(jax.random.PRNGKey(0), 4)
    ops = []
    for k_ in keys:
        x = jax.random.normal(k_, (1, 1, 128, 128), jnp.float32)
        ops.append(x.at[..., 64:].set(0.0))
    q, k, v, _ = ops
    # kernel layout (B, H, S, Dp) -> public layout (B, S, H, D)
    to_pub = lambda x: jnp.transpose(x, (0, 2, 1, 3))[..., :64]  # noqa: E731
    ref = dense_attention(to_pub(q), to_pub(k), to_pub(v), causal=True)
    np.testing.assert_allclose(to_pub(out), ref, atol=1e-4, rtol=1e-4)
    secs = autotune.measure_runner(fn, iters=1, warmup=1)
    assert secs > 0.0
    # the backward/carry runners at least execute end to end
    for kern in ("flash_dq", "flash_dkv", "carry_step"):
        rfn = autotune.make_kernel_runner(kern, (64, 128), **kw)
        jax.block_until_ready(rfn())


# ---- structural pin via the analysis walker (round 13) ----------------------


def test_cpu_flash_trace_structure_via_walker():
    """The analysis walker's census over the CPU flash trace: the dense
    interpret-path fallback must contain matmuls but NO pallas_call and NO
    collectives — the same hermeticity the autotune CPU contract promises,
    pinned structurally rather than by string-matching trace text."""
    from distributed_tensorflow_guide_tpu.analysis import walker

    q, k, v = _qkv(s=64, d=64)
    jaxpr = jax.make_jaxpr(
        lambda q, k, v: flash_attention(q, k, v, causal=True))(q, k, v)
    census = walker.primitive_census(jaxpr)
    assert census["dot_general"] >= 2  # qk^T and pv
    assert census["pallas_call"] == 0
    assert not walker.collective_census(jaxpr)
