"""Anomaly sentinel: NaN/spike detection, rollback via run_with_recovery,
bounded anomaly budget, and the skip-offending escape hatch for persistent
data poison (train/anomaly.py + train/elastic.py wiring)."""

import jax.numpy as jnp
import numpy as np
import pytest

from distributed_tensorflow_guide_tpu.train.anomaly import (
    AnomalyBudgetExceeded,
    AnomalyDetected,
    AnomalySentinelHook,
)
from distributed_tensorflow_guide_tpu.train.checkpoint import Checkpointer
from distributed_tensorflow_guide_tpu.train.elastic import run_with_recovery
from distributed_tensorflow_guide_tpu.train.hooks import StopAtStepHook
from distributed_tensorflow_guide_tpu.train.loop import TrainLoop

TOTAL = 20
CKPT_EVERY = 5


def _step_fn(state, batch):
    params = state["params"]
    grad = 2 * params + batch
    return {"params": params - 0.01 * grad}, {
        "loss": jnp.sum(params ** 2) + jnp.sum(batch) * 0.0
    }


def _init():
    return {"params": jnp.ones((4,))}


def _clean_data(start):
    return (jnp.full((4,), float(s)) for s in range(start, 10_000))


# ---- detection unit behavior -----------------------------------------------


def test_nan_loss_trips_immediately():
    hook = AnomalySentinelHook(budget=5)
    with pytest.raises(AnomalyDetected, match="not finite"):
        hook.after_step(0, {"loss": float("nan")})
    assert hook.trips and hook.trips[0][0] == 0


def test_nan_grad_norm_trips():
    hook = AnomalySentinelHook(budget=5)
    with pytest.raises(AnomalyDetected, match="grad_norm"):
        hook.after_step(0, {"loss": 1.0, "grad_norm": float("inf")})


def test_spike_needs_full_window_then_trips():
    hook = AnomalySentinelHook(spike_factor=10.0, window=5, budget=5)
    for s in range(5):
        hook.after_step(s, {"loss": 1.0})  # fills the window, no trip
    hook.after_step(5, {"loss": 9.0})  # under 10x median: fine
    with pytest.raises(AnomalyDetected, match="spiked"):
        hook.after_step(6, {"loss": 100.0})


def test_warmup_spike_does_not_false_trip():
    hook = AnomalySentinelHook(spike_factor=2.0, window=10, budget=1)
    # wild but finite warmup losses within an unfilled window: no trip
    for s, loss in enumerate([100.0, 3.0, 55.0, 0.1]):
        hook.after_step(s, {"loss": loss})
    assert hook.trips == []


def test_check_every_skips_off_cadence_steps():
    hook = AnomalySentinelHook(check_every=4, budget=5)
    hook.after_step(1, {"loss": float("nan")})  # off-cadence: unchecked
    with pytest.raises(AnomalyDetected):
        hook.after_step(4, {"loss": float("nan")})


def test_grad_norm_spike_trips_on_its_own_history():
    """Review fix: grad-norm gets its OWN median history, so the classic
    optimizer blow-up — grad_norm jumps 100x while loss still looks sane —
    is detected, not just grad-norm non-finiteness."""
    hook = AnomalySentinelHook(spike_factor=10.0, window=4, budget=5)
    for s in range(4):
        hook.after_step(s, {"loss": 1.0, "grad_norm": 2.0})
    with pytest.raises(AnomalyDetected, match="grad_norm=200"):
        hook.after_step(4, {"loss": 1.0, "grad_norm": 200.0})


def test_detection_window_covers_unchecked_steps():
    """With check_every>1 the trip cannot exonerate the unchecked steps
    since the last clean check: the AnomalyDetected window must span them
    (the supervisor skips the whole window, not just the detection step)."""
    hook = AnomalySentinelHook(check_every=5, budget=5)

    class _Loop:
        step = 0

    hook.begin(_Loop())
    hook.after_step(0, {"loss": 1.0})  # clean check -> window starts at 1
    with pytest.raises(AnomalyDetected) as e:
        hook.after_step(5, {"loss": float("nan")})
    assert e.value.window_start == 1 and e.value.step == 5


def test_save_cadence_forces_check_before_save_boundary():
    """run_with_recovery sets save_cadence: the step right before every
    save is checked even when check_every's own cadence misses it — the
    'poison is never persisted' guarantee must be cadence-independent."""
    hook = AnomalySentinelHook(check_every=50, budget=5)
    hook.save_cadence = 5
    hook.after_step(1, {"loss": float("nan")})  # neither cadence: skipped
    with pytest.raises(AnomalyDetected):
        hook.after_step(4, {"loss": float("nan")})  # done=5 save boundary


def test_budget_exceeded_is_not_recoverable_type():
    hook = AnomalySentinelHook(budget=2)
    for step in (0, 1):
        with pytest.raises(AnomalyDetected):
            hook.after_step(step, {"loss": float("nan")})
    with pytest.raises(AnomalyBudgetExceeded):
        hook.after_step(2, {"loss": float("nan")})
    assert not isinstance(AnomalyBudgetExceeded("x"), RuntimeError)


# ---- supervised rollback ----------------------------------------------------


def _run_supervised(make_data, tmpdir, hooks=(), **kw):
    ckpt = Checkpointer(tmpdir, max_to_keep=3)
    try:
        return run_with_recovery(
            _step_fn, _init(), make_data, ckpt,
            hooks=[StopAtStepHook(TOTAL), *hooks],
            checkpoint_every=CKPT_EVERY, **kw,
        )
    finally:
        ckpt.close()


def test_transient_nan_rolls_back_to_bitwise_parity(tmp_path):
    """A one-shot NaN batch trips the sentinel, the supervisor restores the
    last good checkpoint, the replay sees clean data — final params
    bitwise-identical to the uninterrupted run (the crash-equivalence
    oracle extended to the NaN fault class)."""
    clean = _run_supervised(_clean_data, tmp_path / "clean")

    poisoned = [False]

    def poison_once(start):
        for s in range(start, 10_000):
            b = jnp.full((4,), float(s))
            if s == 12 and not poisoned[0]:
                poisoned[0] = True
                b = jnp.full((4,), jnp.nan)
            yield b

    hook = AnomalySentinelHook(budget=3)
    out = _run_supervised(poison_once, tmp_path / "nan", hooks=[hook])
    assert [s for s, _ in hook.trips] == [12]
    np.testing.assert_array_equal(np.asarray(clean["params"]),
                                  np.asarray(out["params"]))


def test_tripped_step_is_never_checkpointed(tmp_path):
    """The sentinel is ordered BEFORE the CheckpointHook inside
    run_with_recovery: a NaN landing exactly on a save boundary must raise
    before the save runs, so no checkpoint ever holds poisoned params."""
    poisoned = [False]

    def poison_on_boundary(start):
        for s in range(start, 10_000):
            b = jnp.full((4,), float(s))
            if s == CKPT_EVERY - 1 and not poisoned[0]:  # step 4 -> save 5
                poisoned[0] = True
                b = jnp.full((4,), jnp.nan)
            yield b

    ckpt = Checkpointer(tmp_path / "b", max_to_keep=10)
    try:
        run_with_recovery(
            _step_fn, _init(), poison_on_boundary, ckpt,
            hooks=[StopAtStepHook(TOTAL), AnomalySentinelHook(budget=3)],
            checkpoint_every=CKPT_EVERY,
        )
        for step in ckpt.all_steps():
            restored = ckpt.restore(_init(), step=step)
            assert np.isfinite(np.asarray(restored["params"])).all(), step
    finally:
        ckpt.close()


def test_persistent_nan_without_skip_burns_budget(tmp_path):
    """Data poison that re-fires on every replay (the underlying stream is
    bad, not a transient): plain rollback loops until the anomaly budget
    stops it loudly."""

    def always_poisoned(start):
        for s in range(start, 10_000):
            yield (jnp.full((4,), jnp.nan) if s == 12
                   else jnp.full((4,), float(s)))

    with pytest.raises(AnomalyBudgetExceeded):
        _run_supervised(always_poisoned, tmp_path / "p",
                        hooks=[AnomalySentinelHook(budget=2)],
                        max_restarts=10)


def test_persistent_nan_with_skip_offending_converges(tmp_path):
    """skip_offending=True drops the poisoned position from the replay:
    the run completes, and the final params equal the oracle trained on
    the stream with that element removed."""

    def always_poisoned(start):
        for s in range(start, 10_000):
            yield (jnp.full((4,), jnp.nan) if s == 12
                   else jnp.full((4,), float(s)))

    hook = AnomalySentinelHook(budget=3, skip_offending=True)
    out = _run_supervised(always_poisoned, tmp_path / "skip", hooks=[hook])

    # oracle: the clean stream with position 12 dropped, run TOTAL steps
    state = _init()
    positions = [p for p in range(TOTAL + 1) if p != 12][:TOTAL]
    for p in positions:
        state, _ = _step_fn(state, jnp.full((4,), float(p)))
    np.testing.assert_array_equal(np.asarray(out["params"]),
                                  np.asarray(state["params"]))
    assert len(hook.trips) == 1  # one trip, then the skip held


def test_persistent_nan_skip_with_coarse_check_cadence(tmp_path):
    """Review fix: with check_every>1 the poison is detected steps after it
    struck; skipping only the detection step would replay the poison
    forever. The whole cannot-exonerate window is skipped instead, so the
    run converges — to the oracle with those positions removed."""

    def always_poisoned(start):
        for s in range(start, 10_000):
            yield (jnp.full((4,), jnp.nan) if s == 7
                   else jnp.full((4,), float(s)))

    hook = AnomalySentinelHook(budget=3, skip_offending=True, check_every=5)
    out = _run_supervised(always_poisoned, tmp_path / "coarse", hooks=[hook])

    # poison hits the params entering step 8; the save-boundary check at
    # step 9 (done=10) trips with window [6..9] -> positions 6..9 skipped
    assert len(hook.trips) == 1
    state = _init()
    positions = [p for p in range(30) if p not in (6, 7, 8, 9)][:TOTAL]
    for p in positions:
        state, _ = _step_fn(state, jnp.full((4,), float(p)))
    np.testing.assert_array_equal(np.asarray(out["params"]),
                                  np.asarray(state["params"]))


def test_budget_exceeded_escapes_run_with_recovery(tmp_path):
    """AnomalyBudgetExceeded is not a RuntimeError: the default recoverable
    filter must let it propagate instead of burning max_restarts on it."""

    def all_nan(start):
        return (jnp.full((4,), jnp.nan) for _ in range(start, 10_000))

    with pytest.raises(AnomalyBudgetExceeded):
        _run_supervised(all_nan, tmp_path / "esc",
                        hooks=[AnomalySentinelHook(budget=1)],
                        max_restarts=50)


def test_sentinel_outside_recovery_plain_loop():
    """Used directly in a TrainLoop (no supervisor), a trip surfaces as the
    AnomalyDetected error — fail-fast rather than silent poison."""
    data = iter([jnp.ones((4,)), jnp.full((4,), jnp.nan), jnp.ones((4,))])

    def step(state, batch):
        return state, {"loss": jnp.sum(batch)}

    loop = TrainLoop(step, {"w": jnp.zeros(2)}, data,
                     hooks=[AnomalySentinelHook(budget=3)])
    with pytest.raises(AnomalyDetected):
        loop.run()
    assert loop.step == 1
