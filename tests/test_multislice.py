"""Two-tier multi-slice strategy + elastic world supervision.

The parity ladder (SURVEY.md §4 applied to the DCN tier):
``sync_period=1`` ≡ sync DP (the LocalSGD pin, re-proved for the
two-level reduction), the full outer round ≡ a host-side oracle of the
same algebra, the DCN collectives fire once per round regardless of
``sync_period`` — and at the top, the elastic acceptance pins: a seeded
slice-loss/regrow run is bitwise reproducible and its stream accounting
shows every sample consumed exactly once across the resize.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from flax.training import train_state

import distributed_tensorflow_guide_tpu.collectives as cc
from distributed_tensorflow_guide_tpu.core.mesh import MeshSpec
from distributed_tensorflow_guide_tpu.parallel.data_parallel import (
    DataParallel,
)
from distributed_tensorflow_guide_tpu.parallel.multislice import (
    DCN_AXIS,
    MultiSliceLocalSGD,
    TwoTierState,
    two_tier_mesh,
)
from distributed_tensorflow_guide_tpu.testing.chaos import (
    Fault,
    FaultSchedule,
)
from distributed_tensorflow_guide_tpu.train.elastic_world import (
    ElasticSupervisor,
    shard_bounds,
    toy_spec,
    verify_stream_accounting,
)

DIM = 6


def _problem(seed=0, n=128):
    rng = np.random.RandomState(seed)
    x = rng.randn(n, DIM).astype(np.float32)
    w_true = rng.randn(DIM, 1).astype(np.float32)
    return x, x @ w_true


def _loss_aux(params, batch):
    pred = batch["x"] @ params["w"]
    return jnp.mean((pred - batch["y"]) ** 2), {}


def _state(tx, seed=0):
    rng = np.random.RandomState(100 + seed)
    params = {"w": jnp.asarray(rng.randn(DIM, 1).astype(np.float32) * 0.1)}
    return train_state.TrainState.create(apply_fn=None, params=params, tx=tx)


def _superbatch(x, y, k, world_batch, seed=7):
    idx = np.random.RandomState(seed).randint(0, len(x), k * world_batch)
    return {
        "x": x[idx].reshape(k, world_batch, DIM),
        "y": y[idx].reshape(k, world_batch, 1),
    }


@pytest.fixture()
def mesh22():
    return two_tier_mesh(MeshSpec(), n_slices=2)


# ---- mesh construction ------------------------------------------------------


def test_two_tier_mesh_axes_and_contiguous_slices(mesh22):
    assert mesh22.axis_names == (DCN_AXIS, "data", "model", "pipe",
                                 "context", "expert")
    assert mesh22.devices.shape == (2, 4, 1, 1, 1, 1)
    # fake devices group contiguously by id: slice 0 = first 4 devices —
    # the process→slice mapping the elastic harness assigns
    ids = np.vectorize(lambda d: d.id)(mesh22.devices)
    assert sorted(ids[0].ravel().tolist()) == [0, 1, 2, 3]
    assert sorted(ids[1].ravel().tolist()) == [4, 5, 6, 7]


def test_two_tier_mesh_rejects_nondivisible_slices():
    with pytest.raises(ValueError, match="do not split"):
        two_tier_mesh(MeshSpec(), n_slices=3)


def test_two_tier_mesh_refuses_to_straddle_real_slices():
    """When devices DO expose slice topology, a disagreeing n_slices must
    raise — contiguous chunking would silently put the per-step inner
    pmean across a real DCN boundary, the exact mistake the module
    exists to prevent. (No-slice-info backends keep the fake split.)"""

    class FakeDev:
        def __init__(self, i):
            self.id = i
            self.slice_index = i // 4
            self.process_index = 0
            self.platform = "tpu"

    devs = [FakeDev(i) for i in range(8)]  # 2 real slices of 4
    mesh = two_tier_mesh(MeshSpec(), devices=devs, n_slices=2)
    slice_of = np.vectorize(lambda d: d.slice_index)(mesh.devices)
    assert np.all(slice_of[0] == 0) and np.all(slice_of[1] == 1)
    with pytest.raises(ValueError, match="span 2 real slice"):
        two_tier_mesh(MeshSpec(), devices=devs, n_slices=4)
    with pytest.raises(ValueError, match="span 2 real slice"):
        two_tier_mesh(MeshSpec(), devices=devs, n_slices=1)


def test_strategy_requires_two_tier_axes(mesh8):
    with pytest.raises(ValueError, match="two_tier_mesh"):
        MultiSliceLocalSGD(mesh8, sync_period=1)


# ---- parity ladder ----------------------------------------------------------


def test_sync_period1_equals_sync_dp(mesh22, mesh8):
    """sync_period=1, outer_lr=1, outer_momentum=0: the two-level
    reduction (within-slice grad pmean, cross-slice param average) IS
    sync DP — the LocalSGD period-1 pin, DCN-tier edition."""
    x, y = _problem()
    ms = MultiSliceLocalSGD(mesh22, sync_period=1)
    dp = DataParallel(mesh8)
    s_ms = ms.replicate(ms.init(_state(optax.sgd(0.05))))
    s_dp = dp.replicate(_state(optax.sgd(0.05)))
    step_ms = ms.make_train_step(_loss_aux, donate=False)
    step_dp = dp.make_train_step(_loss_aux, donate=False)
    for i in range(5):
        sb = _superbatch(x, y, 1, 64, seed=7 + i)
        s_ms, m_ms = step_ms(s_ms, ms.shard_batch(sb))
        s_dp, m_dp = step_dp(
            s_dp, dp.shard_batch({"x": sb["x"][0], "y": sb["y"][0]}))
        assert float(m_ms["loss"]) == pytest.approx(
            float(m_dp["loss"]), rel=1e-5)
    np.testing.assert_allclose(
        np.asarray(s_ms.inner.params["w"]), np.asarray(s_dp.params["w"]),
        rtol=1e-5)


def test_outer_round_matches_host_oracle(mesh22):
    """One compiled outer round ≡ the written-down algebra: per-slice
    sync-DP SGD over the slice's contiguous row block, delta average
    across slices, Nesterov outer update, on a single-mesh host oracle."""
    x, y = _problem()
    k, batch, mu, olr, ilr = 3, 64, 0.9, 0.7, 0.05
    ms = MultiSliceLocalSGD(mesh22, sync_period=k, outer_lr=olr,
                            outer_momentum=mu)
    state = ms.replicate(ms.init(_state(optax.sgd(ilr))))
    step = ms.make_train_step(_loss_aux, donate=False)

    w = np.asarray(state.inner.params["w"]).astype(np.float64)
    m = np.zeros_like(w)
    for r in range(2):
        sb = _superbatch(x, y, k, batch, seed=11 + r)
        state, _ = step(state, ms.shard_batch(sb))

        anchor = w.copy()
        per_slice = []
        for s in range(2):
            lo, hi = shard_bounds(batch, 2, s)
            ws = anchor.copy()
            for j in range(k):
                xs = sb["x"][j, lo:hi].astype(np.float64)
                ys = sb["y"][j, lo:hi].astype(np.float64)
                g = 2.0 * xs.T @ (xs @ ws - ys) / (xs.shape[0] * 1)
                ws = ws - ilr * g
            per_slice.append(ws)
        delta = anchor - np.mean(per_slice, axis=0)
        m = mu * m + delta
        w = anchor - olr * (delta + mu * m)
    np.testing.assert_allclose(
        np.asarray(state.inner.params["w"]), w, rtol=1e-4)


def test_outer_collectives_cross_dcn_once_per_round(mesh22):
    """The bandwidth contract: param-sized DCN collectives fire once per
    OUTER ROUND — the count must not scale with sync_period — while the
    per-inner-step gradient pmean stays on the within-slice axis."""
    x, y = _problem()

    def dcn_calls(sync_period, outer="on"):
        ms = MultiSliceLocalSGD(mesh22, sync_period, outer=outer)
        state = ms.replicate(ms.init(_state(optax.sgd(0.05))))
        with cc.trace_comm() as rec:
            step = ms.make_train_step(_loss_aux, donate=False)
            step.lower(state, ms.shard_batch(
                _superbatch(x, y, sync_period, 64)))
        return {key: n for key, n in rec.calls.items()}

    c1, c4 = dcn_calls(1), dcn_calls(4)
    assert c1[f"pmean[{DCN_AXIS}]"] > 0
    # one outer sync per round at ANY period: identical DCN call count
    assert c1[f"pmean[{DCN_AXIS}]"] == c4[f"pmean[{DCN_AXIS}]"]
    # the dense per-step gradient reduction rides the within-slice axis
    assert c4["pmean[data]"] > 0
    # outer="off" (the bench's timing control) emits NO collective that
    # touches the DCN axis — not even the metric scalar, whose per-round
    # latency would contaminate the exposed-frac control on real DCN
    assert not any(DCN_AXIS in key for key in dcn_calls(4, outer="off"))


def test_outer_sync_bytes_closed_form():
    from benchmarks.common import outer_sync_bytes

    assert outer_sync_bytes(100.0, 1) == 0.0
    assert outer_sync_bytes(100.0, 4) == pytest.approx(2 * 100 * 3 / 4)


def test_outer_float_bytes_counts_params_and_float_opt_state(mesh22):
    # sgd without momentum: float state = params only (6*1 f32 = 24B)
    ms = MultiSliceLocalSGD(mesh22, 1)
    assert ms.outer_float_bytes(ms.init(_state(optax.sgd(0.05)))) == 24
    # with momentum: + the f32 trace (another 24B)
    assert ms.outer_float_bytes(
        ms.init(_state(optax.sgd(0.05, momentum=0.9)))) == 48


def test_two_tier_state_is_a_pytree(mesh22):
    ms = MultiSliceLocalSGD(mesh22, 1)
    tt = ms.init(_state(optax.sgd(0.05)))
    leaves, treedef = jax.tree_util.tree_flatten(tt)
    rebuilt = jax.tree_util.tree_unflatten(treedef, leaves)
    assert isinstance(rebuilt, TwoTierState)
    np.testing.assert_array_equal(
        np.asarray(rebuilt.inner.params["w"]),
        np.asarray(tt.inner.params["w"]))


# ---- deterministic re-split + exactly-once accounting -----------------------


def test_shard_bounds_tile_disjointly():
    for total in (8, 7, 12):
        for n in (1, 2, 3, 5):
            spans = [shard_bounds(total, n, r) for r in range(n)]
            pos = 0
            for lo, hi in spans:
                assert lo == pos
                pos = hi
            assert pos == total
    with pytest.raises(ValueError):
        shard_bounds(8, 2, 2)


def test_verify_stream_accounting_resize_and_replays():
    """The exactly-once verdict: a resize (different tiling per world),
    in-generation replays (later record wins) and superseded crashed-
    generation work all pass; gaps, overlaps and missing rounds fail."""
    B = 8

    def rec(gen, rnd, sl, lo, hi):
        return {"gen": gen, "round": rnd, "slice": sl, "lo": lo, "hi": hi}

    good = [
        # gen 0: two slices, rounds 0-2; round 2's work is superseded
        rec(0, 0, 0, 0, 4), rec(0, 0, 1, 4, 8),
        rec(0, 1, 0, 0, 4), rec(0, 1, 1, 4, 8),
        rec(0, 2, 0, 0, 4), rec(0, 2, 1, 4, 8),
        # gen 1 (reduced world): rounds 2-3 at the new tiling
        rec(1, 2, 0, 0, 8), rec(1, 3, 0, 0, 8),
    ]
    ok, problems = verify_stream_accounting(good, 4, B)
    assert ok, problems

    # in-generation replay of round 3: the later record wins, still ok
    replay = good + [rec(1, 3, 0, 0, 8)]
    ok, _ = verify_stream_accounting(replay, 4, B)
    assert ok

    gap = good[:-1] + [rec(1, 3, 0, 0, 6)]
    ok, problems = verify_stream_accounting(gap, 4, B)
    assert not ok and any("dropped" in p for p in problems)

    overlap = good + [rec(1, 3, 1, 2, 8)]
    ok, problems = verify_stream_accounting(overlap, 4, B)
    assert not ok and any("duplicated" in p for p in problems)

    ok, problems = verify_stream_accounting(good, 5, B)
    assert not ok and any("never consumed" in p for p in problems)


# ---- elastic supervision over real processes --------------------------------

pytestmark_mp = pytest.mark.chaos


@pytest.mark.chaos
def test_elastic_supervisor_clean_run_matches_oracle(tmp_path):
    """A fault-free supervised run over 2 one-process slices ends at the
    host oracle of the same two-tier algebra — pinning the whole worker
    stack (step-keyed stream, contiguous re-split, two-tier step) across
    real process boundaries."""
    spec = toy_spec(total_steps=4, ckpt_every=2, sync_period=2,
                    global_batch=8, dim=4, seed=5)
    sup = ElasticSupervisor(
        FaultSchedule([]), n_slices=2, procs_per_slice=1,
        base_spec=spec, ckpt_dir=tmp_path / "ckpt",
        workdir=tmp_path / "work", timeout=150,
    )
    rep = sup.run()
    assert [e["outcome"] for e in rep.timeline] == ["clean"]
    ok, problems = rep.accounting(4, 8)
    assert ok, problems

    # host oracle of elastic_toy_worker's trajectory
    gt = np.random.RandomState(5)
    w_true = gt.randn(4, 1).astype(np.float32)
    w = np.zeros((4, 1), np.float64)
    for r in range(4):
        anchor = w.copy()
        per_slice = []
        for s in range(2):
            lo, hi = shard_bounds(8, 2, s)
            ws = anchor.copy()
            for k in range(2):
                rng = np.random.RandomState(
                    np.asarray([5, r, k], dtype=np.uint32))
                x = rng.randn(8, 4).astype(np.float32)
                y = x @ w_true
                xs = x[lo:hi].astype(np.float64)
                ys = y[lo:hi].astype(np.float64)
                g = 2.0 * xs.T @ (xs @ ws - ys) / xs[..., :1].size
                ws = ws - 0.05 * g
            per_slice.append(ws)
        w = anchor - (anchor - np.mean(per_slice, axis=0))
    np.testing.assert_allclose(
        np.asarray(rep.final_params), w.reshape(-1), rtol=1e-4)


def _elastic_run(tmp_path, tag):
    sched = FaultSchedule([Fault("slice_loss", 5, 1.0),
                           Fault("slice_return", 10, 1.0)])
    sup = ElasticSupervisor(
        sched, n_slices=2, procs_per_slice=2,
        base_spec=toy_spec(total_steps=16, ckpt_every=4, sync_period=2,
                           global_batch=8, dim=4, seed=3,
                           outer_momentum=0.9, outer_lr=0.7),
        ckpt_dir=tmp_path / tag / "ckpt", workdir=tmp_path / tag / "work",
        timeout=150, failure_grace=5.0,
    )
    return sup.run(), sched


@pytest.mark.chaos
@pytest.mark.slow
def test_slice_loss_resize_regrow_bitwise_and_exactly_once(tmp_path):
    """The round-12 acceptance pin: slice 1 dies after step 5 (all of its
    processes, group-targeted), training continues at reduced world
    within one restore, regrows at step 10, and finishes — with every
    stream index consumed exactly once across both resizes, and two
    identically-seeded runs bitwise identical to each other."""
    rep1, sched1 = _elastic_run(tmp_path, "a")
    outcomes = [e["outcome"] for e in rep1.timeline]
    assert outcomes == ["slice_loss", "clean", "clean"]
    # reduced world really trained (one-generation recovery, not a stall)
    assert rep1.timeline[1]["live"] == [0]
    assert rep1.timeline[1].get("returned") == [1]
    assert rep1.timeline[2]["live"] == [0, 1]
    # both world faults fired exactly once
    assert sched1.world_events() == []
    assert {f.kind for f in sched1.fired} == {"slice_loss", "slice_return"}
    # one resize, one measured recovery
    assert len(rep1.mttr_s) == 1 and rep1.mttr_s[0] > 0
    # exactly-once data accounting across the resize
    ok, problems = rep1.accounting(16, 8)
    assert ok, problems
    # final state identical on every worker of the final generation
    ws = [r.result["w"] for r in rep1.results]
    assert all(w == ws[0] for w in ws)

    rep2, _ = _elastic_run(tmp_path, "b")
    assert rep2.final_params == rep1.final_params  # bitwise, run vs run
    assert [e["outcome"] for e in rep2.timeline] == outcomes


@pytest.mark.chaos
def test_supervisor_raises_on_unscheduled_failure(tmp_path):
    """A generation that dies WITHOUT a scheduled slice loss is a real
    failure — the supervisor must surface it, not shrink the world."""
    from distributed_tensorflow_guide_tpu.train.elastic_world import (
        ElasticWorldError,
    )

    # 2 slices but a batch that cannot split over the devices: every
    # worker raises at startup, no loss marker is ever written
    spec = toy_spec(total_steps=4, ckpt_every=2, global_batch=3)
    sup = ElasticSupervisor(
        FaultSchedule([]), n_slices=2, procs_per_slice=1,
        base_spec=spec, ckpt_dir=tmp_path / "ckpt",
        workdir=tmp_path / "work", timeout=120, failure_grace=3.0,
    )
    with pytest.raises(ElasticWorldError, match="without a scheduled"):
        sup.run()
