"""Pallas flash-attention kernel vs. the pure-XLA oracles.

Runs in interpret mode on the CPU test backend (tests/conftest.py); the same
kernels compile via Mosaic on TPU. Parity target: dense_attention
(ops/attention.py), itself tested against plain softmax.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_tensorflow_guide_tpu.core.compat import shard_map
from distributed_tensorflow_guide_tpu.ops.attention import dense_attention
from distributed_tensorflow_guide_tpu.ops.flash_attention import (
    flash_attention,
    supported,
)


def _qkv(b=2, s=256, h=2, d=64, seed=0, dtype=jnp.float32):
    rng = np.random.RandomState(seed)
    mk = lambda: jnp.asarray(rng.randn(b, s, h, d), dtype)  # noqa: E731
    return mk(), mk(), mk()


@pytest.mark.parametrize("causal", [False, True])
def test_forward_matches_dense(causal):
    q, k, v = _qkv()
    out = flash_attention(q, k, v, causal=causal)
    ref = dense_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(out, ref, atol=2e-2, rtol=2e-2)


@pytest.mark.parametrize("causal", [False, True])
def test_gradients_match_dense(causal):
    q, k, v = _qkv(s=128, h=1)

    def loss(fn):
        return lambda q, k, v: jnp.sum(fn(q, k, v, causal=causal) ** 2)

    g_flash = jax.grad(loss(flash_attention), argnums=(0, 1, 2))(q, k, v)
    g_dense = jax.grad(loss(dense_attention), argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_flash, g_dense):
        scale = float(jnp.max(jnp.abs(b))) + 1e-6
        np.testing.assert_allclose(a / scale, b / scale, atol=2e-2)


def test_head_dim_padding():
    # d=64 pads to one 128 lane; d=32 likewise — both must slice back exactly
    q, k, v = _qkv(s=128, d=32)
    out = flash_attention(q, k, v)
    assert out.shape == q.shape
    ref = dense_attention(q, k, v)
    np.testing.assert_allclose(out, ref, atol=2e-2, rtol=2e-2)


def test_bfloat16_inputs():
    q, k, v = _qkv(s=128, dtype=jnp.bfloat16)
    out = flash_attention(q, k, v, causal=True)
    assert out.dtype == jnp.bfloat16
    ref = dense_attention(q, k, v, causal=True)
    np.testing.assert_allclose(
        out.astype(np.float32), ref.astype(np.float32), atol=8e-2, rtol=8e-2
    )


def test_unsupported_shape_falls_back():
    # S=100 not divisible by the 128 block → pure-XLA blockwise fallback
    assert not supported(100, 64)
    q, k, v = _qkv(s=100)
    out = flash_attention(q, k, v, causal=True)
    ref = dense_attention(q, k, v, causal=True)
    np.testing.assert_allclose(out, ref, atol=2e-2, rtol=2e-2)


def test_flash_under_data_parallel_shard_map():
    # flash's supported composition mode: per-device local arrays inside
    # shard_map (DP/PP/SP strategies); batch axis sharded over "data".
    from jax.sharding import PartitionSpec as P

    from distributed_tensorflow_guide_tpu.core.mesh import MeshSpec, build_mesh

    mesh = build_mesh(MeshSpec(data=-1))
    n = mesh.devices.shape[0]
    q, k, v = _qkv(b=2 * n, s=128)
    sharded = jax.jit(
        shard_map(
            lambda q, k, v: flash_attention(q, k, v, causal=True),
            mesh=mesh,
            in_specs=(P("data"),) * 3,
            out_specs=P("data"),
            check_vma=False,
        )
    )
    out = sharded(q, k, v)
    ref = dense_attention(q, k, v, causal=True)
    np.testing.assert_allclose(out, ref, atol=2e-2, rtol=2e-2)


def test_attn_impl_validated():
    from distributed_tensorflow_guide_tpu.models.transformer import (
        TransformerConfig,
    )

    with pytest.raises(ValueError, match="attn_impl"):
        TransformerConfig(attn_impl="Flash")


def test_transformer_flash_matches_dense():
    from distributed_tensorflow_guide_tpu.models.transformer import (
        Transformer,
        TransformerConfig,
    )

    kw = dict(
        vocab_size=128, num_layers=2, num_heads=2, d_model=32, d_ff=64,
        max_len=128, causal=True, dtype=jnp.float32,
    )
    tokens = jnp.asarray(
        np.random.RandomState(0).randint(0, 128, (2, 128)), jnp.int32
    )
    md = Transformer(TransformerConfig(**kw, attn_impl="dense"))
    mf = Transformer(TransformerConfig(**kw, attn_impl="flash"))
    variables = md.init(jax.random.PRNGKey(0), tokens)
    ld = md.apply(variables, tokens)
    lf = mf.apply(variables, tokens)
    np.testing.assert_allclose(ld, lf, atol=5e-2, rtol=5e-2)


# ---- round-3 hardening (verdict weak item 6) --------------------------------


def test_forward_f32_tight_tolerance():
    """float32 permits far tighter parity than the historical 2e-2: the
    kernel's online softmax and dense softmax agree to ~1e-6 relative."""
    q, k, v = _qkv()
    for causal in (False, True):
        out = flash_attention(q, k, v, causal=causal)
        ref = dense_attention(q, k, v, causal=causal)
        np.testing.assert_allclose(out, ref, atol=1e-4, rtol=1e-4)


def test_bfloat16_gradients_match_dense():
    q, k, v = _qkv(s=128, h=1, dtype=jnp.bfloat16)

    def loss(fn):
        return lambda q, k, v: jnp.sum(
            fn(q, k, v, causal=True).astype(jnp.float32) ** 2
        )

    g_flash = jax.grad(loss(flash_attention), argnums=(0, 1, 2))(q, k, v)
    g_dense = jax.grad(loss(dense_attention), argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_flash, g_dense):
        a = np.asarray(a, np.float32)
        b = np.asarray(b, np.float32)
        scale = np.abs(b).max() + 1e-6
        np.testing.assert_allclose(a / scale, b / scale, atol=6e-2)


def test_causal_grad_with_nonlane_head_dim():
    """The combined case the verdict called out: causal masking + backward
    + head dim that is NOT a multiple of the 128-lane width (d=80 pads to
    128). Zero-padded lanes must be exact no-ops through the backward
    kernels too — gradients in the padding columns never leak."""
    q, k, v = _qkv(s=256, h=2, d=80)

    def loss(fn):
        return lambda q, k, v: jnp.sum(fn(q, k, v, causal=True) ** 2)

    g_flash = jax.grad(loss(flash_attention), argnums=(0, 1, 2))(q, k, v)
    g_dense = jax.grad(loss(dense_attention), argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_flash, g_dense):
        scale = float(jnp.max(jnp.abs(b))) + 1e-6
        np.testing.assert_allclose(a / scale, b / scale, atol=1e-3)


def test_fallback_is_observable(caplog):
    import logging

    from distributed_tensorflow_guide_tpu.ops.flash_attention import (
        fallback_stats,
    )

    q, k, v = _qkv(s=96)  # 96 % 128 != 0 -> blockwise fallback
    before = sum(fallback_stats().values())
    with caplog.at_level(logging.WARNING, logger="dtg.ops.flash"):
        flash_attention(q, k, v)
    after = fallback_stats()
    assert sum(after.values()) == before + 1
    assert ("flash_attention", 96, 64, 128, 128) in after
    # the first fallback for a shape logs a warning
    if before == 0 or ("flash_attention", 96, 64, 128, 128) not in dict(
        (k_, v_) for k_, v_ in after.items() if v_ > 1
    ):
        assert any("falling back" in r.message for r in caplog.records)


def test_in_auto_mesh_probe_pinned():
    """_in_auto_mesh guards the flash<->TP composition. Its legacy-context
    branch imports jax internals (jax 0.9 has no public accessor for the
    legacy ``with mesh:`` context: jax.sharding.get_mesh reads only the
    set_mesh context and raises under tracing). This test FAILS — not
    warns — when a JAX upgrade moves the probe, so flash-under-
    TensorParallel can't silently stop engaging custom_partitioning
    (round-3 verdict weak 6)."""
    import warnings

    import jax.numpy as jnp

    from distributed_tensorflow_guide_tpu.core.mesh import MeshSpec, build_mesh
    from distributed_tensorflow_guide_tpu.ops.flash_attention import (
        _in_auto_mesh,
    )

    mesh = build_mesh(MeshSpec(data=-1))
    with warnings.catch_warnings():
        warnings.simplefilter("error", RuntimeWarning)  # degrade -> failure
        assert _in_auto_mesh() is False  # no mesh context: raw kernel path

        # the real call site runs during jit TRACING under the legacy
        # context — probe must see the mesh there (thread-local env)
        seen = []

        def f(x):
            seen.append(_in_auto_mesh())
            return x

        with mesh:
            jax.jit(f).lower(jnp.zeros(4))
        assert seen == [True]

        # inside shard_map (Manual axes) the raw per-device call is right
        seen_sm = []

        def body(x):
            seen_sm.append(_in_auto_mesh())
            return x

        jax.jit(shard_map(
            body, mesh=mesh, in_specs=jax.sharding.PartitionSpec("data"),
            out_specs=jax.sharding.PartitionSpec("data"), check_vma=False,
        )).lower(jnp.zeros(len(jax.devices())))
        assert seen_sm == [False]
