import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from flax.training import train_state

from distributed_tensorflow_guide_tpu.models.mnist_cnn import MNISTCNN
from distributed_tensorflow_guide_tpu.train import (
    Checkpointer,
    CheckpointHook,
    StopAtStepHook,
    TrainLoop,
)


def _state():
    model = MNISTCNN()
    params = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 28, 28, 1)))["params"]
    return train_state.TrainState.create(
        apply_fn=model.apply, params=params, tx=optax.sgd(0.1)
    )


def test_save_restore_roundtrip(tmp_path):
    state = _state()
    ckpt = Checkpointer(tmp_path / "ckpt")
    ckpt.save(3, state, force=True)
    ckpt.wait()
    assert ckpt.latest_step() == 3
    restored = ckpt.restore(state)
    for a, b in zip(jax.tree.leaves(state), jax.tree.leaves(restored)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    ckpt.close()


def test_restore_missing_raises(tmp_path):
    ckpt = Checkpointer(tmp_path / "empty")
    try:
        import pytest

        with pytest.raises(FileNotFoundError):
            ckpt.restore(_state())
    finally:
        ckpt.close()


def test_checkpoint_hook_saves_periodically_and_at_end(tmp_path):
    ckpt = Checkpointer(tmp_path / "ckpt", max_to_keep=10)

    def step_fn(state, batch):
        return state.replace(step=state.step + 1), {"loss": jnp.float32(0.0)}

    loop = TrainLoop(
        step_fn,
        _state(),
        iter(lambda: 0, 1),
        hooks=[StopAtStepHook(5), CheckpointHook(ckpt, every_steps=2)],
    )
    final = loop.run()
    ckpt.wait()
    assert ckpt.latest_step() == 5  # end-of-run save
    # labels are completed-step counts and must equal the state's own step,
    # so resume never replays an applied update
    for label in (2, 4, 5):
        restored = ckpt.restore(final, step=label)
        assert int(restored.step) == label
    ckpt.close()


def test_resumed_finished_run_is_a_noop(tmp_path):
    state = _state()

    def step_fn(s, batch):
        return s.replace(step=s.step + 1), {}

    loop = TrainLoop(step_fn, state, iter(lambda: 0, 1),
                     hooks=[StopAtStepHook(3)], start_step=3)
    final = loop.run()
    assert loop.step == 3 and int(final.step) == 0  # no extra update executed


def test_resume_continues_from_checkpoint(tmp_path):
    """The MonitoredTrainingSession recovery model: restore + step counter."""
    ckpt = Checkpointer(tmp_path / "ckpt")
    state = _state()

    def step_fn(s, batch):
        return s.replace(step=s.step + 1), {}

    loop = TrainLoop(step_fn, state, iter(lambda: 0, 1), hooks=[StopAtStepHook(3)])
    final = loop.run()
    ckpt.save(int(final.step), final, force=True)
    ckpt.wait()

    # "crash"; new process restores and continues to 6
    start = ckpt.latest_step()
    resumed = ckpt.restore(state)
    loop2 = TrainLoop(
        step_fn, resumed, iter(lambda: 0, 1),
        hooks=[StopAtStepHook(6)], start_step=start,
    )
    final2 = loop2.run()
    assert loop2.step == 6 and int(final2.step) == 6
    ckpt.close()


def test_default_layout_pins_hook_driven_saves(tmp_path):
    """Round-4 advisor: CheckpointHook/PreemptionHook call ckpt.save without
    layout=, so hook-driven checkpoints of a pipelined model carried no
    layout pin. default_layout on the Checkpointer closes that hole: every
    save/restore that doesn't pass layout= inherits it."""
    layout_a = {"schedule": "interleaved", "P": 2, "v": 2}
    ckpt = Checkpointer(tmp_path / "ckpt", default_layout=layout_a)

    def step_fn(state, batch):
        return state.replace(step=state.step + 1), {}

    loop = TrainLoop(
        step_fn, _state(), iter(lambda: 0, 1),
        hooks=[StopAtStepHook(2), CheckpointHook(ckpt, every_steps=2)],
    )
    final = loop.run()
    ckpt.wait()
    assert (tmp_path / "ckpt" / "layout_2.json").exists()
    # same-layout restore (default applied) succeeds
    restored = ckpt.restore(final)
    assert int(restored.step) == 2
    ckpt.close()
    # a permuted model's Checkpointer (different default_layout) refuses
    other = Checkpointer(tmp_path / "ckpt",
                         default_layout={"schedule": "gpipe", "P": 4, "v": 1})
    with pytest.raises(ValueError, match="layout mismatch"):
        other.restore(final)
    # ...unless the caller explicitly opts out with layout=None (foreign-
    # topology inspection must stay expressible on a pinned Checkpointer)
    restored = other.restore(final, layout=None)
    assert int(restored.step) == 2
    other.close()


def test_sharded_fsdp_roundtrip(tmp_path):
    """Sharding-aware checkpointing (SURVEY.md §5 checkpoint row): an FSDP
    (ZeRO-3) state saves from its shards and restores INTO its shards — the
    multi-host recovery path where no device ever holds the full tree."""
    from distributed_tensorflow_guide_tpu.core.mesh import (
        MeshSpec,
        build_mesh,
    )
    from distributed_tensorflow_guide_tpu.models.mnist_cnn import MNISTCNN
    from distributed_tensorflow_guide_tpu.parallel.fsdp import FSDP

    mesh = build_mesh(MeshSpec(data=-1))
    model = MNISTCNN()
    fsdp = FSDP(mesh, min_shard_size=2 ** 10)

    def init_fn():
        return model.init(jax.random.PRNGKey(3), jnp.zeros((1, 28, 28, 1)))[
            "params"
        ]

    params, shardings = fsdp.init_params(init_fn)
    state = train_state.TrainState.create(
        apply_fn=model.apply, params=params, tx=optax.adam(1e-3)
    )
    state = jax.device_put(state, fsdp.state_shardings(state, shardings))

    ckpt = Checkpointer(tmp_path / "fsdp")
    ckpt.save(0, state, force=True)
    ckpt.wait()

    restored = ckpt.restore(state)
    for a, b in zip(jax.tree.leaves(state), jax.tree.leaves(restored),
                    strict=True):
        assert a.sharding == b.sharding, (a.sharding, b.sharding)
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    # the big kernel really is sharded in the restored tree
    big = max(jax.tree.leaves(restored.params), key=lambda l: l.size)
    assert "data" in tuple(s for s in big.sharding.spec if s)
    ckpt.close()


def test_layout_sidecar_refuses_permuted_restore(tmp_path):
    """ADVICE round 3: a (P=2, v=2) interleaved stage stack is
    shape-identical to a (P=4, v=1) stack, so orbax restores one into the
    other silently — with the wrong layer order. The layout sidecar must
    turn that into a loud error (and allow the matching restore)."""
    import jax.numpy as jnp

    from distributed_tensorflow_guide_tpu.core.mesh import MeshSpec, build_mesh
    from distributed_tensorflow_guide_tpu.models.transformer import (
        TransformerConfig,
    )
    from distributed_tensorflow_guide_tpu.parallel.pipeline import PipelinedLM
    from distributed_tensorflow_guide_tpu.train.checkpoint import Checkpointer

    cfg = TransformerConfig(
        vocab_size=64, num_layers=8, num_heads=2, d_model=16, d_ff=32,
        max_len=8, causal=True, dtype=jnp.float32,
    )
    mesh22 = build_mesh(MeshSpec(data=-1, pipe=2))
    pp22 = PipelinedLM(mesh22, cfg, num_microbatches=2, virtual_chunks=2)
    params = pp22.init_params(jax.random.PRNGKey(0))

    ck = Checkpointer(tmp_path / "ck")
    ck.save(1, params, layout=pp22.layout_metadata())
    ck.wait()

    # matching layout restores fine
    restored = ck.restore(params, layout=pp22.layout_metadata())
    assert jax.tree.structure(restored) == jax.tree.structure(params)

    # shape-identical but permuted layout must refuse — first PROVE the
    # premise: the two stage stacks really are indistinguishable by shape
    mesh41 = build_mesh(MeshSpec(data=-1, pipe=4))
    pp41 = PipelinedLM(mesh41, cfg, num_microbatches=2)
    params41 = pp41.init_params(jax.random.PRNGKey(0))
    assert (
        [(leaf.shape, leaf.dtype) for leaf in jax.tree.leaves(params)]
        == [(leaf.shape, leaf.dtype) for leaf in jax.tree.leaves(params41)]
    )
    with pytest.raises(ValueError, match="layout mismatch"):
        ck.restore(params41, layout=pp41.layout_metadata())
    ck.close()


def test_3d_pipeline_checkpoint_restores_into_shards(tmp_path):
    """Save a 3D (dp x tp x pp) PipelinedLM param tree — pipe-sharded stage
    stacks, vocab-sharded embedding and head — and restore it INTO its
    shard layout on the live mesh: every restored leaf must carry the same
    sharding as the original and match numerically (the sharded analogue
    of the FSDP roundtrip, for the round-4 3D layout)."""
    import jax.numpy as jnp

    from distributed_tensorflow_guide_tpu.core.mesh import MeshSpec, build_mesh
    from distributed_tensorflow_guide_tpu.models.transformer import (
        TransformerConfig,
    )
    from distributed_tensorflow_guide_tpu.parallel.pipeline import PipelinedLM
    from distributed_tensorflow_guide_tpu.train.checkpoint import Checkpointer

    cfg = TransformerConfig(
        vocab_size=64, num_layers=4, num_heads=2, d_model=16, d_ff=32,
        max_len=8, causal=True, dtype=jnp.float32,
    )
    mesh = build_mesh(MeshSpec(data=2, pipe=2, model=2))
    pp = PipelinedLM(mesh, cfg, num_microbatches=2, schedule="1f1b",
                     virtual_chunks=2)
    params = pp.init_params(jax.random.PRNGKey(3))

    ck = Checkpointer(tmp_path / "ck3d")
    ck.save(1, params, layout=pp.layout_metadata())
    ck.wait()
    restored = ck.restore(params, layout=pp.layout_metadata())
    for (path, a), (_, b) in zip(
        jax.tree_util.tree_flatten_with_path(params)[0],
        jax.tree_util.tree_flatten_with_path(restored)[0],
        strict=True,
    ):
        assert a.sharding == b.sharding, path
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b),
                                      err_msg=str(path))
    # the vocab-sharded tables really restored as shards, not replicas
    emb = restored["embed"]["tok_emb"]["embedding"]
    assert emb.addressable_shards[0].data.shape[0] == cfg.vocab_size // 2
    ck.close()
