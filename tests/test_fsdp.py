"""FSDP (ZeRO-3 over the data axis): sharding layout + DP-parity.

The contract: fully-sharded training is an EXECUTION layout, not a
different algorithm — same numerics as replicated sync DP, params/moments
actually sharded over ``data``.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
from flax.training import train_state
from jax.sharding import PartitionSpec as P

from distributed_tensorflow_guide_tpu.core.mesh import MeshSpec, build_mesh
from distributed_tensorflow_guide_tpu.models.mnist_cnn import (
    MNISTCNN,
    make_loss_fn,
)
from distributed_tensorflow_guide_tpu.parallel.data_parallel import DataParallel
from distributed_tensorflow_guide_tpu.parallel.fsdp import (
    FSDP,
    shard_spec_for,
)


def test_shard_spec_policy():
    # big divisible dim -> sharded on its largest divisible axis
    assert tuple(shard_spec_for((256, 512), 8)) == (None, "data")
    assert tuple(shard_spec_for((1024, 384), 8)) == ("data", None)
    # tiny leaves (biases/norms) replicate
    assert tuple(shard_spec_for((128,), 8)) == ()
    # indivisible dims replicate rather than pad
    assert tuple(shard_spec_for((270, 130), 8, min_size=1)) == ()


def _setup(lr=0.1):
    mesh = build_mesh(MeshSpec(data=-1))
    model = MNISTCNN()
    fsdp = FSDP(mesh, min_shard_size=2 ** 10)

    def init_fn():
        p = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 28, 28, 1)))
        return p["params"]

    params, shardings = fsdp.init_params(init_fn)
    state = train_state.TrainState.create(
        apply_fn=model.apply, params=params, tx=optax.sgd(lr, momentum=0.9)
    )
    st_sh = fsdp.state_shardings(state, shardings)
    state = jax.device_put(state, st_sh)
    return mesh, model, fsdp, state, st_sh


def test_params_and_moments_actually_sharded():
    mesh, model, fsdp, state, st_sh = _setup()
    # the dense kernel (3136, 128) or conv kernels must be split over data
    sharded_leaves = [
        l for l in jax.tree.leaves(state.params)
        if "data" in tuple(l.sharding.spec)
    ]
    assert sharded_leaves, "no parameter leaf is sharded over data"
    big = max(jax.tree.leaves(state.params), key=lambda l: l.size)
    assert "data" in tuple(s for s in big.sharding.spec if s)
    assert big.addressable_shards[0].data.size == big.size // 8
    # momentum follows
    mu_big = max(jax.tree.leaves(state.opt_state[0].trace),
                 key=lambda l: l.size)
    assert "data" in tuple(s for s in mu_big.sharding.spec if s)


def test_fsdp_matches_replicated_dp():
    from distributed_tensorflow_guide_tpu.data.synthetic import synthetic_mnist

    mesh, model, fsdp, state_f, st_sh = _setup()
    loss_fn = make_loss_fn(model)
    step_f = fsdp.make_train_step(loss_fn, st_sh, donate=False)

    dp = DataParallel(mesh)
    params0 = jax.tree.map(np.asarray, state_f.params)
    state_d = dp.replicate(train_state.TrainState.create(
        apply_fn=model.apply, params=params0,
        tx=optax.sgd(0.1, momentum=0.9),
    ))
    step_d = dp.make_train_step(loss_fn, donate=False)

    for b in synthetic_mnist(32, seed=7).take(5):
        state_f, m_f = step_f(state_f, jax.device_put(
            b, jax.NamedSharding(mesh, P("data"))))
        state_d, m_d = step_d(state_d, dp.shard_batch(b))
        np.testing.assert_allclose(float(m_f["loss"]), float(m_d["loss"]),
                                   rtol=1e-5)

    for a, b_ in zip(jax.tree.leaves(state_f.params),
                     jax.tree.leaves(state_d.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                   rtol=1e-4, atol=1e-6)


def test_fsdp_transformer_trains():
    """FSDP on the transformer (the model family whose size motivates it)."""
    from distributed_tensorflow_guide_tpu.models.transformer import (
        Transformer,
        TransformerConfig,
        make_lm_loss_fn,
    )

    cfg = TransformerConfig(
        vocab_size=256, num_layers=2, num_heads=4, d_model=64, d_ff=128,
        max_len=32, causal=True, dtype=jnp.float32,
    )
    mesh = build_mesh(MeshSpec(data=-1))
    model = Transformer(cfg)
    fsdp = FSDP(mesh, min_shard_size=2 ** 10)
    tokens0 = jnp.zeros((1, cfg.max_len), jnp.int32)

    def init_fn():
        import flax.linen as nn

        return nn.meta.unbox(
            model.init(jax.random.PRNGKey(0), tokens0)
        )["params"]

    params, shardings = fsdp.init_params(init_fn)
    state = train_state.TrainState.create(
        apply_fn=model.apply, params=params, tx=optax.adam(1e-3)
    )
    st_sh = fsdp.state_shardings(state, shardings)
    state = jax.device_put(state, st_sh)
    step = fsdp.make_train_step(make_lm_loss_fn(model), st_sh, donate=False)

    rng = np.random.RandomState(0)
    batch = {"tokens": rng.randint(0, 256, (16, cfg.max_len)).astype(np.int32)}
    losses = []
    for _ in range(15):
        state, m = step(state, batch)
        losses.append(float(m["loss"]))
    assert losses[-1] < losses[0] * 0.85, losses
    # embedding and mlp kernels sharded
    emb = state.params["tok_emb"]["embedding"]
    assert "data" in tuple(s for s in emb.sharding.spec if s)


def test_fsdp_per_device_state_bytes_shrink():
    """The strategy's reason to exist: resident params+moments per device
    shrink ~world-fold vs replicated DP (exact shard-shape accounting, the
    same math benchmarks/bench_fsdp_memory.py reports)."""
    from benchmarks.bench_fsdp_memory import state_bytes
    from distributed_tensorflow_guide_tpu.models.transformer import (
        Transformer,
        TransformerConfig,
    )
    import flax.linen as nn

    cfg = TransformerConfig(
        vocab_size=4096, num_layers=2, num_heads=4, d_model=256, d_ff=1024,
        max_len=32, causal=True, dtype=jnp.float32,
    )
    mesh = build_mesh(MeshSpec(data=-1))
    model = Transformer(cfg)
    fsdp = FSDP(mesh)
    tokens0 = jnp.zeros((1, cfg.max_len), jnp.int32)

    def init_fn():
        return nn.meta.unbox(
            model.init(jax.random.PRNGKey(0), tokens0)
        )["params"]

    params, shardings = fsdp.init_params(init_fn)
    state = train_state.TrainState.create(
        apply_fn=model.apply, params=params, tx=optax.adam(1e-3)
    )
    state = jax.device_put(state, fsdp.state_shardings(state, shardings))

    sharded = state_bytes(state, sharded=True)
    replicated = state_bytes(state, sharded=False)
    # big matrices (embeddings, attn/mlp kernels + their two adam moments)
    # dominate; only biases/norms stay replicated
    assert replicated / sharded > 6, (sharded, replicated)
