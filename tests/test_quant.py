"""Quantization across the stack (round 19): the ops/quant primitives,
the weight-only decode path, the int8 training preset, and the
int8-compressed gradient collectives.

The load-bearing pins:

* the FUSED dequant never materializes a scaled f32 kernel copy — no
  kernel-shaped f32 multiply exists anywhere in the trace, and the cost
  interpreter charges the matmul's kernel read at the STORED width
  (narrow-origin accounting), so the byte diet is real, not cosmetic;
* int4 pack/unpack is a bitwise round trip over the whole nibble grid;
* the wq8 engine reproduces its own one-shot oracle bitwise AND the f32
  greedy stream exactly at the small geometry (the accuracy pin — int4
  is lossier and pins a logit tolerance instead);
* ``int8_ste_dot`` really contracts int8 x int8 -> int32 and its VJP is
  bit-identical to the unquantized matmul's (straight-through);
* compressed collectives move 1/4 the float bytes plus a 4-byte scale
  and stay inside the shared-scale error bound.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from flax.training import train_state

import distributed_tensorflow_guide_tpu.collectives as cc
from distributed_tensorflow_guide_tpu.analysis import cost as cost_mod
from distributed_tensorflow_guide_tpu.analysis import lint
from distributed_tensorflow_guide_tpu.analysis import rules as rules_mod
from distributed_tensorflow_guide_tpu.analysis import walker
from distributed_tensorflow_guide_tpu.analysis.contracts import (
    ProgramContract,
)
from distributed_tensorflow_guide_tpu.core import precision
from distributed_tensorflow_guide_tpu.core.compat import shard_map
from distributed_tensorflow_guide_tpu.core.mesh import MeshSpec
from distributed_tensorflow_guide_tpu.models.generation import (
    decode_cache_bytes_per_step,
    decode_hbm_bytes_per_step,
    make_generate_fn,
)
from distributed_tensorflow_guide_tpu.models.transformer import (
    Transformer,
    TransformerConfig,
)
from distributed_tensorflow_guide_tpu.ops import quant
from distributed_tensorflow_guide_tpu.parallel.data_parallel import (
    DataParallel,
)
from distributed_tensorflow_guide_tpu.parallel.multislice import (
    MultiSliceLocalSGD,
    two_tier_mesh,
)
from jax.sharding import PartitionSpec as P

CFG = TransformerConfig(vocab_size=64, num_layers=2, num_heads=2,
                        d_model=16, d_ff=32, max_len=64, causal=True,
                        dtype=jnp.float32)


@pytest.fixture(scope="module")
def params():
    return Transformer(CFG).init(
        jax.random.PRNGKey(0), jnp.zeros((2, 8), jnp.int32))["params"]


# ---- the storage-side primitives --------------------------------------------


@pytest.mark.parametrize("bits", [8, 4])
def test_quantize_roundtrip_error_bound(bits):
    """Round-to-nearest on a symmetric per-column grid: every element of
    the dequantized kernel is within scale/2 of the original, and an
    all-zero column maps to scale 1 (never 0/0) and exact zeros."""
    rng = np.random.RandomState(0)
    w = rng.randn(32, 8).astype(np.float32)
    w[:, 3] = 0.0
    q, scale = quant.quantize_channelwise(jnp.asarray(w), bits=bits)
    assert q.dtype == jnp.int8 and scale.shape == (8,)
    assert int(jnp.max(jnp.abs(q))) <= quant.QMAX[bits]
    back = np.asarray(quant.dequantize_channelwise(q, scale))
    assert np.all(np.abs(back - w) <= np.asarray(scale)[None, :] / 2 + 1e-7)
    assert float(scale[3]) == 1.0
    assert np.all(back[:, 3] == 0.0)


def test_pack_unpack_int4_bitwise():
    """The whole [-8, 7] nibble grid survives pack -> unpack bit-for-bit
    (quantize only emits [-7, 7], but the packing layer must be exact on
    the full two's-complement range), and odd leading axes are refused."""
    grid = jnp.asarray(np.arange(-8, 8, dtype=np.int8).reshape(16, 1))
    assert np.array_equal(np.asarray(quant.unpack_int4(quant.pack_int4(grid))),
                          np.asarray(grid))
    rng = np.random.RandomState(1)
    q = jnp.asarray(rng.randint(-7, 8, (64, 5)).astype(np.int8))
    packed = quant.pack_int4(q)
    assert packed.shape == (32, 5) and packed.dtype == jnp.uint8
    assert np.array_equal(np.asarray(quant.unpack_int4(packed)),
                          np.asarray(q))
    with pytest.raises(ValueError, match="even leading axis"):
        quant.pack_int4(q[:63])


@pytest.mark.parametrize("bits", [8, 4])
def test_wq_matmul_matches_unfused_oracle(bits):
    """(x @ q) * s == x @ (q * s): the scale is constant along the
    contracted axis so the fused form is the same algebra — parity with
    the materializing reference stays at float-rounding level."""
    rng = np.random.RandomState(2)
    x = jnp.asarray(rng.randn(4, 64).astype(np.float32))
    w = jnp.asarray(rng.randn(64, 32).astype(np.float32))
    q, scale = quant.quantize_channelwise(w, bits=bits)
    stored = quant.pack_int4(q) if bits == 4 else q
    got = quant.wq_matmul(x, stored, scale, bits=bits)
    ref = x @ quant.dequantize_channelwise(q, scale)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


def _kernel_shaped_f32_muls(jaxpr, shape):
    return [e for e in walker.walk(jaxpr)
            for v in e.outvars
            if e.primitive.name == "mul"
            and tuple(v.aval.shape) == shape
            and v.aval.dtype == jnp.float32]


def test_fused_dequant_never_materializes_scaled_kernel():
    """The structural half of the fusion promise: the scale lands on the
    OUTPUT columns, so no f32 multiply anywhere in the trace produces a
    kernel-shaped value (the unfused reference is the positive control —
    it produces exactly that). The byte half: the cost interpreter's
    narrow-origin accounting charges the fused matmul's kernel read at
    int8 width, 3 bytes/elem less than the unfused program pays."""
    rng = np.random.RandomState(3)
    x = jnp.asarray(rng.randn(4, 64).astype(np.float32))
    q, scale = quant.quantize_channelwise(
        jnp.asarray(rng.randn(64, 32).astype(np.float32)))

    fused = jax.make_jaxpr(lambda x, q, s: quant.wq_matmul(x, q, s))(
        x, q, scale)
    unfused = jax.make_jaxpr(
        lambda x, q, s: x @ quant.dequantize_channelwise(q, s))(
        x, q, scale)
    assert not _kernel_shaped_f32_muls(fused.jaxpr, (64, 32))
    assert len(_kernel_shaped_f32_muls(unfused.jaxpr, (64, 32))) == 1

    def _read(jx):
        traced = rules_mod.TracedProgram(
            name="wq", jaxpr=jx,
            arg_leaf_avals=[[jax.ShapeDtypeStruct(a.shape, a.dtype)]
                            for a in (x, q, scale)])
        contract = ProgramContract(name="wq", build=lambda: None)
        return cost_mod.program_cost(traced, contract).hbm_bytes_read

    assert _read(unfused) - _read(fused) == 3 * 64 * 32


# ---- quantize_params + the decode roofline ----------------------------------


def test_quantize_params_structure_and_pure(params):
    """Every projection kernel becomes {qkernel, scale} (the layout
    WeightQuantDense consumes), biases and LayerNorms ride through, and
    the f32 source tree is untouched (pure function)."""
    before = jax.tree.leaves(params)
    qp = quant.quantize_params(params, bits=8)
    for a, b in zip(before, jax.tree.leaves(params)):
        assert a is b
    found = 0

    def walk(node):
        nonlocal found
        if not isinstance(node, dict):
            return
        for name, child in node.items():
            if name in quant.WQ_PROJECTIONS and isinstance(child, dict) \
                    and "qkernel" in child:
                found += 1
                assert "kernel" not in child
                assert child["qkernel"].dtype == jnp.int8
                assert child["scale"].dtype == jnp.float32
            else:
                walk(child)

    walk(qp)
    # qkv/proj/up/down per layer x 2 layers + lm_head
    assert found == 4 * CFG.num_layers + 1


@pytest.mark.parametrize("bits,lo,hi", [(8, 2.5, 4.5), (4, 4.0, 8.5)])
def test_decode_roofline_params_term_shrinks(params, bits, lo, hi):
    """decode_hbm_bytes_per_step is leaf-driven, so handing it the
    quantized tree shrinks the params term toward ~4x (int8) / ~8x
    (packed int4). At this tiny d_out the per-column f32 scales and the
    untouched bias/LayerNorm leaves dilute the ratio well below the pure
    storage factor (the bench at GPT-2 geometry lands ~3.8x/~7.4x),
    hence the wide bands."""
    cfg_q = dataclasses.replace(
        CFG, weight_dtype="int8" if bits == 8 else "int4")
    qp = quant.quantize_params(params, bits=bits)
    cache = decode_cache_bytes_per_step(CFG, 1)
    full = decode_hbm_bytes_per_step(CFG, params, 1) - cache
    slim = decode_hbm_bytes_per_step(cfg_q, qp, 1) - cache
    assert lo <= full / slim <= hi


# ---- serving accuracy pins --------------------------------------------------


def _one_shot(cfg, prm, prompt, max_new, temp=0.0, top_k=None):
    gen = make_generate_fn(cfg, max_new_tokens=max_new, temperature=temp,
                           top_k=top_k)
    out = gen(prm, prompt[None], jax.random.PRNGKey(100))
    return np.asarray(out)[0, len(prompt):].tolist()


def test_wq8_engine_matches_one_shot_and_f32_greedy(params):
    """The weight-only int8 acceptance pin at the small geometry: the
    engine on the quantized config reproduces its own one-shot oracle
    bitwise (same lever code on both sides), and the greedy stream is
    token-identical to the f32 model's — int8 per-column error is far
    below the argmax margins here."""
    from distributed_tensorflow_guide_tpu.serve.engine import (
        Request,
        ServeEngine,
    )

    cfg_q = dataclasses.replace(CFG, weight_dtype="int8")
    qp = quant.quantize_params(params, bits=8)
    prompts = [np.array([3, 5, 7, 9, 11], np.int32),
               np.array([2, 4, 6, 8, 10, 12, 14, 16, 18], np.int32)]
    max_new = [8, 6]
    eng = ServeEngine(cfg_q, qp, temperature=0.0, top_k=None, slots=2,
                      num_blocks=17, block_size=8, prefill_chunk=8)
    for i, (p, mn) in enumerate(zip(prompts, max_new)):
        eng.submit(Request(rid=i, prompt=p, max_new_tokens=mn,
                           rng=jax.random.PRNGKey(100 + i)))
    eng.run()
    got = eng.completions()
    for i, (p, mn) in enumerate(zip(prompts, max_new)):
        assert got[i] == _one_shot(cfg_q, qp, p, mn), f"req {i} vs wq8"
        assert got[i] == _one_shot(CFG, params, p, mn), f"req {i} vs f32"
    eng.sched.pool.check_leaks()


def test_wq4_logits_within_tolerance(params):
    """int4 is lossy enough to flip low-margin greedy tokens (no bitwise
    stream guarantee — docs/serving.md says so out loud); the pin is a
    logit-space tolerance against the f32 oracle at this geometry."""
    cfg_q = dataclasses.replace(CFG, weight_dtype="int4")
    qp = quant.quantize_params(params, bits=4)
    x = jnp.asarray(np.array([[3, 5, 7, 9, 11, 2, 4, 6]], np.int32))
    lf = Transformer(CFG).apply({"params": params}, x)
    lq = Transformer(cfg_q).apply({"params": qp}, x)
    assert float(jnp.max(jnp.abs(lf - lq))) < 0.05


# ---- AQT-style int8 training matmuls ----------------------------------------


def test_int8_ste_dot_contracts_int8_and_grads_are_straight_through():
    """The trace really contains an int8 x int8 -> int32 contraction (the
    MXU-native mode the rules gate legalizes), the forward stays within
    the two-operand quantization bound, and the VJP is bit-identical to
    the unquantized matmul's — the straight-through contract."""
    rng = np.random.RandomState(4)
    x = jnp.asarray(rng.randn(4, 16).astype(np.float32))
    w = jnp.asarray(rng.randn(16, 8).astype(np.float32))
    jx = jax.make_jaxpr(quant.int8_ste_dot)(x, w)
    dots = [e for e in walker.walk(jx.jaxpr)
            if e.primitive.name == "dot_general"]
    assert [str(v.aval.dtype) for v in dots[0].invars] == ["int8", "int8"]
    assert str(dots[0].outvars[0].aval.dtype) == "int32"

    ref = x @ w
    rel = float(jnp.max(jnp.abs(quant.int8_ste_dot(x, w) - ref))
                / jnp.max(jnp.abs(ref)))
    assert rel < 0.05

    _, vjp_q = jax.vjp(quant.int8_ste_dot, x, w)
    _, vjp_f = jax.vjp(lambda a, b: a @ b, x, w)
    ct = jnp.asarray(rng.randn(4, 8).astype(np.float32))
    for got, want in zip(vjp_q(ct), vjp_f(ct)):
        assert np.array_equal(np.asarray(got), np.asarray(want))


def test_int8_policy_loss_parity_with_f32():
    """PRESETS["int8"] trains the tiny LM step-for-step against "f32" —
    same f32 masters, same everything except the projection contraction
    representation, so the loss curves track within a tight band."""
    small = dataclasses.replace(CFG, max_len=32)

    def train(cfg, steps=5):
        model = Transformer(cfg)
        prm = model.init(jax.random.PRNGKey(0),
                         jnp.zeros((2, 8), jnp.int32))["params"]
        tx = optax.adam(1e-2)
        opt = tx.init(prm)
        xs = np.random.RandomState(0).randint(
            0, cfg.vocab_size, (steps, 4, 8)).astype(np.int32)

        @jax.jit
        def step(prm, opt, x):
            def loss_fn(p):
                lp = jax.nn.log_softmax(
                    model.apply({"params": p}, x[:, :-1]), -1)
                return -jnp.mean(jnp.take_along_axis(
                    lp, x[:, 1:, None], -1))

            loss, g = jax.value_and_grad(loss_fn)(prm)
            up, opt = tx.update(g, opt, prm)
            return optax.apply_updates(prm, up), opt, loss

        out = []
        for x in xs:
            prm, opt, loss = step(prm, opt, x)
            out.append(float(loss))
        return out

    l_f32 = train(precision.PRESETS["f32"].apply_to_transformer(small))
    l_int8 = train(precision.PRESETS["int8"].apply_to_transformer(small))
    for a, b in zip(l_f32, l_int8):
        assert abs(a - b) / a < 5e-3


# ---- int8-compressed gradient collectives -----------------------------------


def test_int8_pmean_parity_bytes_and_passthrough(mesh8):
    """One shared-scale bucket over 8 devices: the mean lands within
    scale/2 of the exact pmean, the wire carries exactly 1 byte/elem of
    float payload plus the single 4-byte scale pmax, and integer leaves
    (and all-integer trees) never touch a collective."""
    rng = np.random.RandomState(5)
    tree = {"w": jnp.asarray(rng.randn(8, 16, 4).astype(np.float32)),
            "b": jnp.asarray(rng.randn(8, 4).astype(np.float32)),
            "count": jnp.arange(8, dtype=jnp.int32)}
    specs = {"w": P("data"), "b": P("data"), "count": P("data")}
    fn = jax.jit(shard_map(lambda t: quant.int8_pmean(t, "data"),
                           mesh=mesh8, in_specs=(specs,), out_specs=specs,
                           check_vma=False))
    with cc.trace_comm() as rec:
        jax.eval_shape(fn, tree)
    # per-device payload: (1,16,4)+(1,4) float elems in int8 + 4B scale
    assert dict(rec.bytes) == {"pmax[data]": 4, "psum[data]": 68}

    got = fn(tree)
    n = 8
    amax = float(max(jnp.max(jnp.abs(tree["w"])), jnp.max(jnp.abs(tree["b"]))))
    bound = amax / (127 // n) / 2 + 1e-7
    for key in ("w", "b"):
        ref = jnp.broadcast_to(jnp.mean(tree[key], axis=0, keepdims=True),
                               tree[key].shape)
        assert float(jnp.max(jnp.abs(got[key] - ref))) <= bound
    assert np.array_equal(np.asarray(got["count"]),
                          np.asarray(tree["count"]))

    ints = jax.jit(shard_map(lambda t: quant.int8_pmean(t, "data"),
                             mesh=mesh8, in_specs=({"count": P("data")},),
                             out_specs={"count": P("data")},
                             check_vma=False))
    with cc.trace_comm() as rec2:
        jax.eval_shape(ints, {"count": tree["count"]})
    assert dict(rec2.bytes) == {}


def _toy_state(dim=8, seed=5):
    rng = np.random.RandomState(seed)
    return train_state.TrainState.create(
        apply_fn=None,
        params={"w": jnp.asarray(rng.randn(dim, 1).astype(np.float32)
                                 * 0.1)},
        tx=optax.sgd(0.05))


def _toy_loss(params, batch):
    err = batch["x"] @ params["w"] - batch["y"]
    return jnp.mean(err ** 2), {}


def test_dp_compress_parity_and_wire_savings(mesh8):
    """compress="int8" on the bucketed backward: training tracks the
    uncompressed run (the gradients-tolerate-it bet, pinned), and the
    traced wire swaps the f32 grad pmean (4 bytes/elem) for an int8 psum
    (1 byte/elem) plus the 4-byte scale pmax side-channel — the metric
    pmean is identical on both sides."""
    dim = 8
    xs = np.random.RandomState(7).randn(64, dim).astype(np.float32)
    batch = {"x": xs, "y": (xs @ np.ones((dim, 1)) * 0.3).astype(np.float32)}
    dp_c = DataParallel(mesh8, overlap=True, bucket_bytes=64,
                        compress="int8")
    dp_p = DataParallel(mesh8, overlap=True, bucket_bytes=64)
    sc, sp = dp_c.replicate(_toy_state()), dp_p.replicate(_toy_state())
    step_c = dp_c.make_train_step(_toy_loss, donate=False)
    step_p = dp_p.make_train_step(_toy_loss, donate=False)
    for _ in range(10):
        sc, mc = step_c(sc, dp_c.shard_batch(batch))
        sp, mp = step_p(sp, dp_p.shard_batch(batch))
    assert float(mc["loss"]) == pytest.approx(float(mp["loss"]), rel=2e-2)
    assert float(jnp.max(jnp.abs(sc.params["w"] - sp.params["w"]))) < 5e-3

    def _traced(dp, state):
        # fresh wrappers: an already-called jitted step would hit the
        # jaxpr cache and skip the python body, recording nothing
        with cc.trace_comm() as rec:
            jax.eval_shape(dp.make_train_step(_toy_loss, donate=False),
                           state, dp.shard_batch(batch))
        return rec.bytes

    plain, comp = _traced(dp_p, sp), _traced(dp_c, sc)
    # one (dim, 1) f32 param -> one bucket; + the 4-byte loss pmean
    assert dict(plain) == {"pmean[data]": 4 * dim + 4}
    assert dict(comp) == {"psum[data]": dim,  # 1 byte/elem on the wire
                          "pmax[data]": 4,    # one bucket -> one scale
                          "pmean[data]": 4}


def test_multislice_compress_parity_and_traced_outer_bytes():
    """The DiLoCo-style outer lever: compressed outer sync tracks the
    uncompressed run, the closed form prices the int8 wire at P/4, and
    the traced DCN payloads reconcile with it exactly (scale pmaxes
    included — plain SGD has no float opt-state, so only the delta
    bucket fires one)."""
    from benchmarks.common import dp_allreduce_bytes, outer_sync_bytes

    mesh22 = two_tier_mesh(MeshSpec(), n_slices=2)
    dim = 8
    xs = np.random.RandomState(9).randn(64, dim).astype(np.float32)
    sb = {"x": xs.reshape(2, 32, dim),
          "y": (xs @ np.ones((dim, 1)) * 0.3).astype(
              np.float32).reshape(2, 32, 1)}
    ms_c = MultiSliceLocalSGD(mesh22, sync_period=2, compress="int8")
    ms_p = MultiSliceLocalSGD(mesh22, sync_period=2)
    s_c = ms_c.replicate(ms_c.init(_toy_state(dim)))
    s_p = ms_p.replicate(ms_p.init(_toy_state(dim)))
    step_c = ms_c.make_train_step(_toy_loss, donate=False)
    step_p = ms_p.make_train_step(_toy_loss, donate=False)
    for _ in range(5):
        s_c, m_c = step_c(s_c, ms_c.shard_batch(sb))
        s_p, m_p = step_p(s_p, ms_p.shard_batch(sb))
    assert float(m_c["loss"]) == pytest.approx(float(m_p["loss"]),
                                               rel=2e-2)
    assert float(jnp.max(jnp.abs(
        s_c.inner.params["w"] - s_p.inner.params["w"]))) < 5e-3

    float_bytes = ms_c.outer_float_bytes(s_c)
    modeled = outer_sync_bytes(float_bytes, 2, compress="int8")
    assert modeled == outer_sync_bytes(float_bytes, 2) / 4
    modeled += 1 * dp_allreduce_bytes(4, 2)  # delta scale pmax only
    with cc.trace_comm() as rec:
        jax.eval_shape(ms_c.make_train_step(_toy_loss, donate=False),
                       s_c, ms_c.shard_batch(sb))
    traced = sum(2.0 * b * (2 - 1) / 2 for key, b in rec.bytes.items()
                 if key.endswith("[dcn]"))
    assert traced == modeled


# ---- the rules gate for integer matmuls -------------------------------------


def _sds(shape, dtype=jnp.float32):
    return jax.ShapeDtypeStruct(shape, dtype)


def _prec(contract):
    report = lint.run_contracts([contract])
    rep = report.programs[0]
    return next(r for r in rep.rules if r.rule == "precision")


def test_int_dot_requires_quantized_matmuls_opt_in():
    def _build():
        return jax.jit(quant.int8_ste_dot), (_sds((4, 16)), _sds((16, 8)))

    prec = _prec(ProgramContract(name="int_dot_no_optin", build=_build))
    assert prec.observed["int_matmuls"] == 1
    assert any("quantized_matmuls" in f.message for f in prec.findings)

    prec = _prec(ProgramContract(name="int_dot_optin", build=_build,
                                 quantized_matmuls=True))
    assert prec.observed["int_matmuls"] == 1
    assert not prec.findings


def test_quantized_dot_must_rescale_and_accumulate_int32():
    from jax import lax

    def _never_rescaled():
        def f(x):
            q = x.astype(jnp.int8)
            return lax.dot_general(
                q, q, dimension_numbers=(((0,), (0,)), ((), ())),
                preferred_element_type=jnp.int32)

        return jax.jit(f), (_sds((16, 8)),)

    prec = _prec(ProgramContract(name="never_rescaled",
                                 build=_never_rescaled,
                                 quantized_matmuls=True))
    assert any("never rescaled" in f.message for f in prec.findings)

    def _int8_accum():
        def f(x):
            q = x.astype(jnp.int8)
            return lax.dot_general(
                q, q, dimension_numbers=(((0,), (0,)), ((), ()))
            ).astype(jnp.float32) * 0.5

        return jax.jit(f), (_sds((16, 8)),)

    prec = _prec(ProgramContract(name="int8_accum", build=_int8_accum,
                                 quantized_matmuls=True))
    assert any("accumulates in" in f.message for f in prec.findings)


# ---- autotune hermeticity for the compressed bucket key ---------------------


def test_compressed_bucket_key_cpu_defaults_only(isolated_autotune_table):
    """The compressed wire tunes under its own dtype key (np.int8) — and
    that key obeys the same CPU defaults-only contract as every other:
    no reads, no writes, no sweeps in tier-1."""
    import json
    import os
    from pathlib import Path

    from distributed_tensorflow_guide_tpu.ops import autotune

    path = Path(os.environ["DTG_AUTOTUNE_TABLE"])
    got = autotune.bucket_bytes_for(param_bytes=1 << 20, world=8,
                                    dtype=np.int8)
    assert got == autotune.DEFAULT_BUCKET_BYTES
    with pytest.raises(RuntimeError, match="defaults-only"):
        autotune.bucket_record(param_bytes=1 << 20, world=8,
                               dtype=np.int8, bucket_bytes=1 << 19)
    assert not path.exists() or json.loads(path.read_text() or "{}") == {}


@pytest.mark.parametrize("bits", [8, 4])
def test_wq_bank_matmul_matches_per_expert_wq_matmul(bits):
    """The expert-bank form (PR 19) is wq_matmul applied expert by
    expert — bitwise, since each expert's rows run the identical fused
    contraction. Also pins the widened-transient discipline: no f32
    tensor of the WHOLE bank's shape appears in the jaxpr (each
    expert's kernel widens alone)."""
    rng = np.random.RandomState(3)
    E, C, D, F = 4, 6, 16, 32
    x = jnp.asarray(rng.randn(E, C, D).astype(np.float32))
    bank = jnp.asarray(rng.randn(E, D, F).astype(np.float32))
    q, scale = jax.vmap(
        lambda k: quant.quantize_channelwise(k, bits=bits))(bank)
    stored = jax.vmap(quant.pack_int4)(q) if bits == 4 else q
    got = quant.wq_bank_matmul(x, stored, scale, bits=bits)
    assert got.shape == (E, C, F)
    for e in range(E):
        ref = quant.wq_matmul(x[e], stored[e], scale[e], bits=bits)
        assert np.array_equal(np.asarray(got[e]), np.asarray(ref)), e
    jaxpr = jax.make_jaxpr(
        lambda a, b, s: quant.wq_bank_matmul(a, b, s, bits=bits))(
        x, stored, scale)
    whole_bank = [v for eqn in walker.walk(jaxpr) for v in eqn.outvars
                  if tuple(v.aval.shape) == (E, D, F)
                  and v.aval.dtype == jnp.float32]
    assert not whole_bank, "dequantized bank materialized at full width"


def test_quantize_params_folds_expert_banks():
    """quantize_params recognizes 3-D (E, d_in, d_out) bank kernels
    under the WQ_BANKS names and emits per-expert qkernel+scale; the
    f32 router projection is exempt (routing is precision-sensitive)."""
    rng = np.random.RandomState(4)
    params = {
        "mlp": {
            "router": {"kernel": rng.randn(16, 4).astype(np.float32)},
            "w_in": {"kernel": rng.randn(4, 16, 32).astype(np.float32)},
            "w_out": {"kernel": rng.randn(4, 32, 16).astype(np.float32)},
        },
    }
    out = quant.quantize_params(params, bits=8)
    assert out["mlp"]["w_in"]["qkernel"].shape == (4, 16, 32)
    assert out["mlp"]["w_in"]["qkernel"].dtype == jnp.int8
    assert out["mlp"]["w_in"]["scale"].shape == (4, 32)
    assert out["mlp"]["w_out"]["scale"].shape == (4, 16)
    assert out["mlp"]["router"]["kernel"].dtype == jnp.float32
    # per-expert channelwise: bank slice e quantizes exactly like the
    # 2-D kernel it is
    q0, s0 = quant.quantize_channelwise(
        jnp.asarray(params["mlp"]["w_in"]["kernel"][0]), bits=8)
    assert np.array_equal(np.asarray(out["mlp"]["w_in"]["qkernel"][0]),
                          np.asarray(q0))
    assert np.array_equal(np.asarray(out["mlp"]["w_in"]["scale"][0]),
                          np.asarray(s0))
