"""Byte-level BPE tokenizer + text-record pipeline (data/tokenizer.py).

The contracts the LM configs rely on: exact roundtrip for arbitrary input
(byte fallback, no <unk>), deterministic training, vocab persistence, and
corpus -> records -> loader parity with direct tokenization (native and
Python loaders byte-identical, as everywhere else in data/).
"""

import numpy as np
import pytest

from distributed_tensorflow_guide_tpu.data.native_loader import (
    PyRecordLoader,
    load_native_lib,
    open_record_loader,
)
from distributed_tensorflow_guide_tpu.data.tokenizer import (
    ByteBPETokenizer,
    ByteTokenizer,
    import_text,
    text_fields,
)

CORPUS = (
    "the quick brown fox jumps over the lazy dog. " * 40
    + "pack my box with five dozen liquor jugs! " * 30
    + "héllo wörld — ünïcode ✓ 测试 " * 10
)

HARD_CASES = [
    "",
    "plain ascii",
    "  leading and trailing  ",
    "tabs\tand\nnewlines\r\n",
    "\x00\x01\x02 control bytes \x7f",
    "héllo wörld — ünïcode ✓",
    "测试中文 with mixed ascii",
    "🙂🙃 emoji pairs 👩‍👩‍👧‍👧",
    "never-seen-at-training xyzzy qwfpgj",
]


@pytest.fixture(scope="module")
def bpe():
    return ByteBPETokenizer.train(CORPUS, vocab_size=512)


@pytest.mark.parametrize("text", HARD_CASES)
def test_bpe_roundtrip_exact(bpe, text):
    assert bpe.decode(bpe.encode(text)) == text


@pytest.mark.parametrize("text", HARD_CASES)
def test_byte_tokenizer_roundtrip_exact(text):
    bt = ByteTokenizer()
    ids = bt.encode(text)
    assert bt.decode(ids) == text
    assert all(0 <= i < 256 for i in ids)


def test_bpe_compresses_training_distribution(bpe):
    ids = bpe.encode(CORPUS)
    n_bytes = len(CORPUS.encode())
    assert len(ids) < n_bytes / 2, (len(ids), n_bytes)
    assert max(ids) >= 256  # merges actually used
    assert bpe.vocab_size == 256 + len(bpe.merges) + 1
    assert bpe.eos_id == bpe.vocab_size - 1


def test_bpe_training_is_deterministic():
    a = ByteBPETokenizer.train(CORPUS, vocab_size=400)
    b = ByteBPETokenizer.train(CORPUS, vocab_size=400)
    assert a.merges == b.merges


def test_bpe_save_load_identity(bpe, tmp_path):
    p = tmp_path / "vocab.json"
    bpe.save(p)
    again = ByteBPETokenizer.load(p)
    assert again.merges == bpe.merges
    for text in HARD_CASES:
        assert again.encode(text) == bpe.encode(text)
    (tmp_path / "bad.json").write_text('{"format": "other"}')
    with pytest.raises(ValueError, match="vocab file"):
        ByteBPETokenizer.load(tmp_path / "bad.json")


def test_bpe_rejects_tiny_vocab():
    with pytest.raises(ValueError, match="258"):
        ByteBPETokenizer.train("x", vocab_size=257)


def test_import_text_records_match_direct_tokenization(bpe, tmp_path):
    """Loader parity: the records stream exactly encode(corpus)+[EOS],
    windowed — through BOTH loaders."""
    corpus = tmp_path / "c.txt"
    corpus.write_text(CORPUS)
    rec = tmp_path / "c.records"
    seq_len = 32
    n = import_text(corpus, rec, bpe, seq_len)

    expect = bpe.encode(CORPUS) + [bpe.eos_id]
    assert n == len(expect) // seq_len
    want = np.asarray(expect[: n * seq_len], np.int32).reshape(n, seq_len)

    py = PyRecordLoader(rec, text_fields(seq_len), batch_size=n,
                        shuffle=False)
    np.testing.assert_array_equal(py.next_batch()["tokens"], want)

    if load_native_lib() is not None:
        native = open_record_loader(rec, text_fields(seq_len), batch_size=n,
                                    shuffle=False)
        np.testing.assert_array_equal(native.next_batch()["tokens"], want)
        native.close()


def test_import_text_rewrites_clean(bpe, tmp_path):
    """A re-import must replace the record file, not append to it."""
    corpus = tmp_path / "c.txt"
    corpus.write_text(CORPUS)
    rec = tmp_path / "c.records"
    n1 = import_text(corpus, rec, bpe, 32)
    n2 = import_text(corpus, rec, bpe, 32)
    assert n1 == n2
    assert rec.stat().st_size == n1 * 32 * 4


def test_giant_pretoken_bounded(bpe):
    """Whitespace-free input (base64 blob / minified JS) must encode in
    bounded time AND still roundtrip exactly (pre-tokens are capped, not
    dropped)."""
    import time

    blob = "QUJDREVGR0hJSktMTU5PUA==" * 8000  # ~200 KB, no whitespace
    t0 = time.time()
    ids = bpe.encode(blob)
    assert time.time() - t0 < 10.0
    assert bpe.decode(ids) == blob


def test_import_text_too_small_raises(bpe, tmp_path):
    corpus = tmp_path / "tiny.txt"
    corpus.write_text("ab")
    with pytest.raises(ValueError, match="seq_len"):
        import_text(corpus, tmp_path / "t.records", bpe, 4096)


# -- labeled text (classification records, config 3) --------------------------


@pytest.fixture(scope="module")
def labeled_tsv(tmp_path_factory):
    p = tmp_path_factory.mktemp("labeled") / "data.tsv"
    lines = [
        "1\tthe film was great and warm",
        "0\tbleak and broken plot",
        "1\tsuperb honest delightful scenes",
        "0\tsour awful ending",
        "1\tcrisp bright dialogue",
        "0\tmurky shallow pacing",
    ]
    p.write_text("\n".join(lines) + "\n")
    return p, lines


def test_import_labeled_text_roundtrip(bpe, labeled_tsv, tmp_path):
    from distributed_tensorflow_guide_tpu.data.tokenizer import (
        import_labeled_text,
        labeled_text_fields,
    )

    tsv, lines = labeled_tsv
    seq = 24
    rec = tmp_path / "d.records"
    n = import_labeled_text(tsv, rec, bpe, seq)
    assert n == len(lines)
    fields = labeled_text_fields(seq)
    ld = PyRecordLoader(rec, fields, batch_size=n, shuffle=False)
    b = ld.next_batch()
    for i, line in enumerate(lines):
        label, text = line.split("\t", 1)
        assert b["label"][i] == int(label)
        ids = bpe.encode(text.encode())[:seq]
        want = ids + [bpe.eos_id] * (seq - len(ids))
        np.testing.assert_array_equal(b["tokens"][i], want)


def test_import_labeled_text_truncates_long_lines(bpe, tmp_path):
    from distributed_tensorflow_guide_tpu.data.tokenizer import (
        import_labeled_text,
        labeled_text_fields,
    )

    tsv = tmp_path / "long.tsv"
    tsv.write_text("0\t" + "word " * 500 + "\n")
    rec = tmp_path / "long.records"
    seq = 16
    assert import_labeled_text(tsv, rec, bpe, seq) == 1
    ld = PyRecordLoader(rec, labeled_text_fields(seq), batch_size=1,
                        shuffle=False)
    b = ld.next_batch()
    assert b["tokens"].shape == (1, seq)
    np.testing.assert_array_equal(
        b["tokens"][0], bpe.encode(("word " * 500).encode())[:seq])


def test_import_labeled_text_rejects_malformed(bpe, tmp_path):
    from distributed_tensorflow_guide_tpu.data.tokenizer import (
        import_labeled_text,
    )

    for bad, match in [("no tab here", "label<TAB>text"),
                       ("x\ttext", "label<TAB>text"),
                       ("", "no examples")]:
        tsv = tmp_path / "bad.tsv"
        tsv.write_text(bad + "\n" if bad else "")
        with pytest.raises(ValueError, match=match):
            import_labeled_text(tsv, tmp_path / "bad.records", bpe, 8)


def test_import_labeled_text_chunked_append(bpe, labeled_tsv, tmp_path):
    """Chunked writes must concatenate to the same file as one shot."""
    from distributed_tensorflow_guide_tpu.data.tokenizer import (
        import_labeled_text,
    )

    tsv, lines = labeled_tsv
    a, b = tmp_path / "a.records", tmp_path / "b.records"
    import_labeled_text(tsv, a, bpe, 24, chunk_records=2)
    import_labeled_text(tsv, b, bpe, 24)
    assert a.read_bytes() == b.read_bytes()
