"""R4/R5/R6 coverage: Hogwild/DOWNPOUR/ADAG — device-level synchronous
mappings + host-side exact-semantics emulation (SURVEY.md §2c)."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from flax.training import train_state

import distributed_tensorflow_guide_tpu.collectives as cc
from distributed_tensorflow_guide_tpu.parallel.async_ps import (
    AccumulatedAdaptive,
    GossipSGD,
    LocalSGD,
)
from distributed_tensorflow_guide_tpu.parallel.data_parallel import DataParallel
from distributed_tensorflow_guide_tpu.parallel.ps_emulator import AsyncPSEmulator

DIM = 6


def _problem(seed=0, n=128):
    rng = np.random.RandomState(seed)
    x = rng.randn(n, DIM).astype(np.float32)
    w_true = rng.randn(DIM, 1).astype(np.float32)
    y = x @ w_true
    return x, y


def _loss(params, batch):
    pred = batch["x"] @ params["w"]
    return jnp.mean((pred - batch["y"]) ** 2)


def _loss_aux(params, batch):
    return _loss(params, batch), {}


def _state(tx, seed=0):
    rng = np.random.RandomState(100 + seed)
    params = {"w": jnp.asarray(rng.randn(DIM, 1).astype(np.float32) * 0.1)}
    return train_state.TrainState.create(
        apply_fn=None, params=params, tx=tx
    )


def _superbatch(x, y, k, world_batch):
    """Leaves (k, world_batch, ...) — k sub-batches per sync round."""
    idx = np.random.RandomState(7).randint(0, len(x), k * world_batch)
    return {
        "x": x[idx].reshape(k, world_batch, DIM),
        "y": y[idx].reshape(k, world_batch, 1),
    }


# ---- LocalSGD (DOWNPOUR-equivalent) -----------------------------------------


def test_local_sgd_period1_equals_sync_dp(mesh8):
    x, y = _problem()
    ls = LocalSGD(mesh8, sync_period=1)
    dp = DataParallel(mesh8)
    s_ls = ls.replicate(_state(optax.sgd(0.05)))
    s_dp = dp.replicate(_state(optax.sgd(0.05)))

    step_ls = ls.make_train_step(_loss_aux, donate=False)
    step_dp = dp.make_train_step(_loss_aux, donate=False)
    for i in range(5):
        sb = _superbatch(x, y, 1, 64)
        s_ls, _ = step_ls(s_ls, ls.shard_batch(sb, leading_time_axis=True))
        flat = {"x": sb["x"][0], "y": sb["y"][0]}
        s_dp, _ = step_dp(s_dp, dp.shard_batch(flat))
    np.testing.assert_allclose(
        np.asarray(s_ls.params["w"]), np.asarray(s_dp.params["w"]), rtol=1e-5
    )


def test_local_sgd_learns_and_syncs(mesh8):
    x, y = _problem()
    ls = LocalSGD(mesh8, sync_period=4)
    state = ls.replicate(_state(optax.sgd(0.05)))
    step = ls.make_train_step(_loss_aux, donate=False)
    losses = []
    for i in range(10):
        state, m = step(state, ls.shard_batch(_superbatch(x, y, 4, 64),
                                              leading_time_axis=True))
        losses.append(float(m["loss"]))
    assert losses[-1] < losses[0] * 0.1, losses
    assert int(state.step) == 40  # counts local steps


def test_local_sgd_comm_every_k_steps(mesh8):
    """The DOWNPOUR bandwidth story: one param-sized collective per K local
    steps (vs K gradient collectives for sync DP)."""
    x, y = _problem()
    ls = LocalSGD(mesh8, sync_period=4)
    state = ls.replicate(_state(optax.sgd(0.05)))
    with cc.trace_comm() as rec:
        step = ls.make_train_step(_loss_aux, donate=False)
        step.lower(state, ls.shard_batch(_superbatch(x, y, 4, 64),
                                         leading_time_axis=True))
    # params pmean (1 leaf) + opt_state pmean (sgd: trace has no float leaves
    # or momentum) + 1 loss pmean, each counted once or twice (shard_map
    # double-trace); crucially NOT 4x per local step
    assert rec.total_calls() <= 2 * 3


# ---- GossipSGD (Hogwild-equivalent) -----------------------------------------


def test_gossip_zero_lr_contracts_disagreement(mesh8):
    gs = GossipSGD(mesh8)
    state = gs.distribute(_state(optax.sgd(0.0)))
    # manually de-synchronize replicas
    w = np.asarray(state.params["w"])  # (8, DIM, 1)
    w = w + np.random.RandomState(0).randn(*w.shape).astype(np.float32)
    state = state.replace(params={"w": jax.device_put(jnp.asarray(w),
                                                      state.params["w"].sharding)})
    x, y = _problem()
    batch = {"x": x[:64].reshape(64, DIM), "y": y[:64].reshape(64, 1)}
    step = gs.make_train_step(_loss_aux, donate=False)
    spread0 = float(np.ptp(np.asarray(state.params["w"]), axis=0).max())
    # ring gossip contracts at the mixing matrix's second eigenvalue
    # (~0.85/step for an 8-ring at mix=0.5), so give it 15 steps
    for _ in range(15):
        state, _ = step(state, gs.shard_batch(batch))
    spread1 = float(np.ptp(np.asarray(state.params["w"]), axis=0).max())
    assert spread1 < spread0 * 0.2, (spread0, spread1)


def test_gossip_learns(mesh8):
    x, y = _problem()
    gs = GossipSGD(mesh8)
    state = gs.distribute(_state(optax.sgd(0.05)))
    step = gs.make_train_step(_loss_aux, donate=False)
    losses = []
    rng = np.random.RandomState(3)
    for _ in range(30):
        idx = rng.permutation(len(x))[:64]
        batch = {"x": x[idx], "y": y[idx]}
        state, m = step(state, gs.shard_batch(batch))
        losses.append(float(m["loss"]))
    assert losses[-1] < losses[0] * 0.1, losses
    w_bar = gs.consensus(state)
    assert w_bar["w"].shape == (DIM, 1)


# ---- AccumulatedAdaptive (ADAG-equivalent) ----------------------------------


def test_adag_equals_large_batch_adam(mesh8):
    """Accumulating K sub-batch grads + one Adam step == one Adam step on the
    concatenated batch (grad of mean == mean of sub-grads)."""
    x, y = _problem()
    aa = AccumulatedAdaptive(mesh8, accum_steps=4)
    state = aa.replicate(_state(optax.adam(0.01)))
    ref = _state(optax.adam(0.01))

    sb = _superbatch(x, y, 4, 64)
    step = aa.make_train_step(_loss_aux, donate=False)
    state, m = step(state, aa.shard_batch(sb, leading_time_axis=True))

    big = {"x": sb["x"].reshape(-1, DIM), "y": sb["y"].reshape(-1, 1)}
    g = jax.grad(_loss)(ref.params, big)
    ref = ref.apply_gradients(grads=g)
    np.testing.assert_allclose(
        np.asarray(state.params["w"]), np.asarray(ref.params["w"]),
        rtol=1e-4, atol=1e-6,
    )


# ---- host-side exact async semantics (parity harness) -----------------------


def _data_iter(x, y, batch=16, seed=0):
    rng = np.random.RandomState(seed)
    while True:
        idx = rng.randint(0, len(x), batch)
        yield {"x": jnp.asarray(x[idx]), "y": jnp.asarray(y[idx])}


def test_hogwild_one_worker_is_plain_sgd():
    x, y = _problem()
    params = {"w": jnp.zeros((DIM, 1))}
    em = AsyncPSEmulator(_loss, params, n_workers=1, mode="hogwild", lr=0.05)
    em.run(_data_iter(x, y, seed=1), 20)

    # sequential SGD on the identical batch stream
    p = {"w": jnp.zeros((DIM, 1))}
    it = _data_iter(x, y, seed=1)
    gfn = jax.jit(jax.grad(_loss))
    for _ in range(20):
        g = gfn(p, next(it))
        p = jax.tree.map(lambda a, b: a - 0.05 * b, p, g)
    np.testing.assert_allclose(
        np.asarray(em.ps_params["w"]), np.asarray(p["w"]), rtol=1e-5
    )


@pytest.mark.parametrize("mode,fetch", [("hogwild", 1), ("downpour", 4), ("adag", 4)])
def test_async_emulation_learns(mode, fetch):
    x, y = _problem()
    params = {"w": jnp.zeros((DIM, 1))}
    em = AsyncPSEmulator(
        _loss, params, n_workers=4, mode=mode, lr=0.05, fetch_period=fetch, seed=2
    )
    losses = em.run(_data_iter(x, y, seed=3), 200)
    assert np.mean(losses[-10:]) < np.mean(losses[:10]) * 0.2, (mode, losses[:3], losses[-3:])


def test_hogwild_reads_are_fresh():
    """Hogwild workers must read CURRENT PS params at each event — a worker
    scheduled for the first time after many updates by others sees all of
    them (staleness comes only from event interleaving)."""
    x, y = _problem()
    params = {"w": jnp.zeros((DIM, 1))}
    em = AsyncPSEmulator(_loss, params, n_workers=2, mode="hogwild", lr=0.05,
                         seed=0)
    it = _data_iter(x, y, seed=9)
    for _ in range(10):
        em._event(0, next(it))  # only worker 0 runs
    loss_before = float(_loss(em.ps_params, next(it)))
    # worker 1's first event: with fresh reads its gradient is taken at the
    # 10-updates-in params, so it cannot undo progress back toward the init
    em._event(1, next(it))
    loss_after = float(_loss(em.ps_params, next(it)))
    assert loss_after < loss_before * 1.5  # continues from current state
    # and its update must differ from what the INITIAL params would produce
    g_fresh = jax.grad(_loss)(em.ps_params, next(it))
    g_stale = jax.grad(_loss)(params, next(it))
    assert not np.allclose(np.asarray(g_fresh["w"]), np.asarray(g_stale["w"]))


def test_downpour_push_cadence():
    x, y = _problem()
    params = {"w": jnp.zeros((DIM, 1))}
    em = AsyncPSEmulator(
        _loss, params, n_workers=2, mode="downpour", lr=0.05, fetch_period=5, seed=4
    )
    em.run(_data_iter(x, y, seed=5), 50)
    assert em.pushes == sum(e // 5 for e in em.events)


def test_device_sync_vs_emulated_async_delta():
    """The documented semantic delta: sync LocalSGD and async DOWNPOUR reach
    the same optimum but along different trajectories."""
    x, y = _problem()
    params = {"w": jnp.zeros((DIM, 1))}
    em = AsyncPSEmulator(
        _loss, params, n_workers=4, mode="downpour", lr=0.05, fetch_period=4, seed=6
    )
    em_losses = em.run(_data_iter(x, y, seed=7), 200)
    assert np.mean(em_losses[-5:]) < 1e-3  # both converge; trajectories differ
