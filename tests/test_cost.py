"""The static cost auditor (analysis/cost.py + fingerprint.py): the
interpreter's arithmetic on known programs, the ring collective model,
donation-aware liveness, and — the point of the suite — every new
failure mode demonstrated to actually FAIL: a peak-live budget blown, a
byte model drifted beyond tolerance, a dead donation charged as live,
and a fingerprint mutated without a bless. Each assertion lands on the
specific finding or drift line, not just report.ok.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from distributed_tensorflow_guide_tpu.analysis import cost, fingerprint, lint
from distributed_tensorflow_guide_tpu.analysis.contracts import (
    CostPin,
    CostSpec,
    DonationSpec,
    ProgramContract,
)
from distributed_tensorflow_guide_tpu.core.compat import shard_map
from distributed_tensorflow_guide_tpu.core.mesh import MeshSpec, build_mesh


def _lint_one(contract):
    report = lint.run_contracts([contract])
    assert len(report.programs) == 1
    return report.programs[0]


def _cost_rule(program_report):
    return next(r for r in program_report.rules if r.rule == "cost")


def _sds(shape, dtype=jnp.float32):
    return jax.ShapeDtypeStruct(shape, dtype)


def _vec(fn, *args, contract=None):
    contract = contract or ProgramContract(name="probe", build=lambda: None)
    traced_jaxpr = jax.make_jaxpr(fn)(*args)

    class _T:
        jaxpr = traced_jaxpr
        arg_leaf_avals = [[a] for a in args]

    return cost.program_cost(_T(), contract)


# ---- interpreter arithmetic on known programs -------------------------------


def test_matmul_flops_and_fusion_boundary_bytes():
    """(8,16)@(16,4) f32: FLOPs = 2*m*k*n; HBM = operands read once,
    output written once; peak = both inputs + the output live together."""
    vec = _vec(lambda x, w: x @ w, _sds((8, 16)), _sds((16, 4)))
    assert vec.flops == 2 * 8 * 16 * 4
    assert vec.hbm_bytes_read == (8 * 16 + 16 * 4) * 4
    assert vec.hbm_bytes_written == 8 * 4 * 4
    assert vec.peak_live_bytes == (8 * 16 + 16 * 4 + 8 * 4) * 4


def test_elementwise_chain_is_fusion_free():
    """tanh/add/mul chains charge ZERO HBM traffic (XLA fuses them) —
    the convention that makes the derived numbers comparable to the
    minimal-traffic closed forms in benchmarks/common.py."""
    vec = _vec(lambda x: jnp.tanh(x * 2.0) + 1.0, _sds((128,)))
    assert vec.flops == 0
    assert vec.hbm_bytes == 0


def test_scan_trip_count_multiplies_body_cost():
    def stepper(c, _):
        return jnp.tanh(c @ c), None

    def fn(c):
        out, _ = jax.lax.scan(stepper, c, None, length=5)
        return out

    vec = _vec(fn, _sds((4, 4)))
    assert vec.flops == 5 * (2 * 4 * 4 * 4)


def test_collective_bytes_ring_model():
    """psum inside shard_map prices at the ring closed form:
    2 * P * (n-1)/n per device, keyed by the census spelling."""
    mesh = build_mesh(MeshSpec(data=-1))
    n = np.prod(list(mesh.shape.values()))

    def step(x):
        return jax.lax.psum(x, "data")

    fn = shard_map(step, mesh=mesh, in_specs=P("data"), out_specs=P())
    per_device = 64 // n
    vec = _vec(fn, _sds((64,)))
    want = 2.0 * (per_device * 4) * (n - 1) / n
    assert vec.collective_bytes == {"psum[data]": want}
    assert vec.quantity("collective_bytes[psum[data]]") == want
    # absent keys resolve to 0.0 — the exact-zero pin mechanism the
    # multislice outer=off contract uses
    assert vec.quantity("collective_bytes[psum[dcn]]") == 0.0


# ---- donation-aware liveness ------------------------------------------------


def test_donated_and_used_input_dies_at_last_use():
    """After `big`'s last use, a donated buffer frees — so the peak over
    the later phase drops by exactly big's bytes vs the undonated run."""

    def fn(big, x):
        h = jnp.sum(big) + x          # big's last (only) use
        # the post-use phase (two 16 KiB tensors) dwarfs big's 4 KiB, so
        # the peak lands AFTER big dies and the donated-vs-not delta is
        # exactly big's footprint
        return jnp.concatenate([x] * 512) * h[:1]

    jaxpr = jax.make_jaxpr(fn)(_sds((1024,)), _sds((8,)))
    donated = cost.peak_live_bytes(jaxpr, donated_flat=frozenset({0}))
    undonated = cost.peak_live_bytes(jaxpr, donated_flat=frozenset())
    assert undonated - donated == 1024 * 4


def test_dead_donation_stays_live():
    """A donated-but-NEVER-READ input cannot alias anything: XLA drops
    the donation and the buffer sits allocated for the whole program —
    the auditor charges it as live, so donating it buys nothing."""

    def fn(big, x):
        return x * 2.0                # big is dead

    jaxpr = jax.make_jaxpr(fn)(_sds((1024,)), _sds((8,)))
    dead_donated = cost.peak_live_bytes(jaxpr, donated_flat=frozenset({0}))
    undonated = cost.peak_live_bytes(jaxpr, donated_flat=frozenset())
    assert dead_donated == undonated
    assert dead_donated >= 1024 * 4


def test_alias_donation_zeroes_passthrough_copy():
    """A state->state passthrough output costs a defensive copy UNLESS
    its input is donated in alias mode — the visible byte delta between
    donate=True and donate=False on the same train step."""

    def fn(state, x):
        return state, jnp.sum(x)

    def contract(donation):
        return ProgramContract(name="p", build=lambda: None,
                               donation=donation)

    aliased = _vec(fn, _sds((256,)), _sds((8,)),
                   contract=contract(DonationSpec(argnums=(0,))))
    copied = _vec(fn, _sds((256,)), _sds((8,)), contract=contract(None))
    assert copied.hbm_bytes - aliased.hbm_bytes == 2 * 256 * 4  # r + w


# ---- failure modes: each must produce its specific finding ------------------


def _matmul_contract(name, cost_spec):
    def _build():
        return (lambda x, w: x @ w), (_sds((8, 16)), _sds((16, 4)))

    return ProgramContract(name=name, build=_build, collectives={},
                           cost=cost_spec)


def test_peak_live_over_budget_fails():
    rep = _lint_one(_matmul_contract(
        "viol_peak", CostSpec(max_peak_live_bytes=100)))
    assert not rep.ok
    [finding] = _cost_rule(rep).findings
    assert "peak live bytes" in finding.message
    assert "over the declared" in finding.message
    assert finding.observed == (8 * 16 + 16 * 4 + 8 * 4) * 4


def test_byte_model_mismatch_beyond_tolerance_fails():
    rep = _lint_one(_matmul_contract(
        "viol_bytes", CostSpec(pins=(
            CostPin("hbm_bytes", 999_999.0, rel_tol=0.01,
                    note="deliberately wrong closed form"),))))
    assert not rep.ok
    [finding] = _cost_rule(rep).findings
    assert "hbm_bytes drifted from the closed-form model" in finding.message
    assert "deliberately wrong closed form" in finding.message
    assert finding.observed == (8 * 16 + 16 * 4 + 8 * 4) * 4


def test_exact_and_tolerant_pins_pass():
    """Positive control: exact pins on the derived numbers, a callable
    expectation (the closed-form-lambda mechanism the providers use),
    and a tolerant pin just inside its band."""
    rep = _lint_one(_matmul_contract(
        "ok_pins", CostSpec(pins=(
            CostPin("flops", 2 * 8 * 16 * 4),
            CostPin("hbm_bytes_written", lambda: 8 * 4 * 4),
            CostPin("flops", 2 * 8 * 16 * 4 * 1.05, rel_tol=0.1),),
            max_peak_live_bytes=4096)))
    assert rep.ok, [f.message for r in rep.rules for f in r.findings]


def test_uninterpretable_trace_with_pins_fails_without_pins_observes():
    """Interpreter crash semantics: observe-only when the contract pins
    nothing (fake-jaxpr micro-programs), a FAIL finding when a CostSpec
    declared numbers it now cannot verify."""

    class _Boom:
        def __getattr__(self, name):
            raise RuntimeError("not a jaxpr")

    from distributed_tensorflow_guide_tpu.analysis import rules

    traced = rules.TracedProgram(name="x", jaxpr=_Boom(),
                                 arg_leaf_avals=[])
    observe = rules.rule_cost(traced, ProgramContract(
        name="x", build=lambda: None))
    assert observe.ok and "error" in observe.observed

    pinned = rules.rule_cost(traced, ProgramContract(
        name="x", build=lambda: None,
        cost=CostSpec(pins=(CostPin("flops", 1.0),))))
    assert not pinned.ok
    assert "cost interpreter failed" in pinned.findings[0].message


# ---- fingerprints: drift gates, bless path ----------------------------------


def test_fingerprint_drift_without_bless_then_bless(tmp_path):
    golden = tmp_path / "goldens.json"

    def contract(scale):
        def _build():
            return (lambda x: x * scale), (_sds((4,)),)

        return ProgramContract(name="fp_prog", build=_build, collectives={})

    rep1 = lint.run_contracts([contract(2.0)])
    lint.bless_fingerprints(rep1, "initial", golden_path=golden)
    lint.check_fingerprints(rep1, full_registry=False, golden_path=golden)
    assert rep1.fingerprint_drift == [] and rep1.ok

    # mutate the program (2.0 -> 3.0): structure hash moves; the SAME
    # goldens must now flag drift and flip the report to FAIL
    rep2 = lint.run_contracts([contract(3.0)])
    lint.check_fingerprints(rep2, full_registry=False, golden_path=golden)
    assert rep2.fingerprint_drift and not rep2.ok
    assert any("fp_prog" in line and "structure" in line
               for line in rep2.fingerprint_drift)

    # the bless path: rewrite goldens with a reason, drift clears
    lint.bless_fingerprints(rep2, "intentional retrace", golden_path=golden)
    goldens = fingerprint.load_goldens(golden)
    assert goldens["fp_prog"]["reason"] == "intentional retrace"
    rep3 = lint.run_contracts([contract(3.0)])
    lint.check_fingerprints(rep3, full_registry=False, golden_path=golden)
    assert rep3.fingerprint_drift == [] and rep3.ok


def test_cost_only_drift_is_caught(tmp_path):
    """Same structure hash, different cost vector (a pure-cost change,
    e.g. an aval growing) must still drift — the fingerprint is the
    PAIR, not just the normalized jaxpr text."""
    golden = tmp_path / "goldens.json"
    rep = lint.run_contracts([ProgramContract(
        name="cv_prog", collectives={},
        build=lambda: ((lambda x: x @ x), (_sds((4, 4)),)))])
    lint.bless_fingerprints(rep, "initial", golden_path=golden)

    fp = rep.programs[0].fingerprint
    mutated = fingerprint.Fingerprint(
        program=fp.program, structure=fp.structure,
        cost=dict(fp.cost, flops=fp.cost["flops"] + 1))
    lines = fingerprint.diff_fingerprint(
        mutated, fingerprint.load_goldens(golden))
    assert lines and any("flops" in line for line in lines)


def test_bless_refuses_failing_registry(tmp_path):
    rep = lint.run_contracts([_matmul_contract(
        "viol_refuse", CostSpec(max_peak_live_bytes=1))])
    with pytest.raises(RuntimeError, match="refusing to bless"):
        lint.bless_fingerprints(rep, "nope",
                                golden_path=tmp_path / "g.json")


def test_stale_golden_flagged_on_full_registry(tmp_path):
    """A golden whose program no longer exists is drift on a full run
    (deleting a judged program silently would un-gate it forever)."""
    golden = tmp_path / "goldens.json"
    rep = lint.run_contracts([ProgramContract(
        name="live_prog", collectives={},
        build=lambda: ((lambda x: x + 1.0), (_sds((4,)),)))])
    lint.bless_fingerprints(rep, "initial", golden_path=golden)
    ghost = fingerprint.Fingerprint(program="ghost_prog",
                                    structure="0" * 64, cost={})
    fingerprint.save_goldens(
        [rep.programs[0].fingerprint, ghost], "adds ghost", path=golden)

    lint.check_fingerprints(rep, full_registry=True, golden_path=golden)
    assert any("ghost_prog" in line for line in rep.fingerprint_drift)
    # partial runs (--programs) must NOT flag it: absence is not evidence
    rep2 = lint.run_contracts([ProgramContract(
        name="live_prog", collectives={},
        build=lambda: ((lambda x: x + 1.0), (_sds((4,)),)))])
    lint.check_fingerprints(rep2, full_registry=False, golden_path=golden)
    assert rep2.fingerprint_drift == []


def test_shipped_goldens_match_registry_names():
    """The committed golden file covers exactly the registered programs
    (names only — the hashes themselves are verified by the bench_lint
    tier-1 subprocess at the pinned 8-device geometry)."""
    goldens = fingerprint.load_goldens()
    live = {c.name for c in lint._registered(None)}
    assert set(goldens) == live


# ---- kernel cost registry ---------------------------------------------------


def test_registered_decode_kernel_model_prices_pallas_call():
    """The decode-attention kernels' registered models price a traced
    pallas_call at decode_kernel_hbm_bytes exactly — auditor and kernel
    microbench can never disagree about the same call."""
    from distributed_tensorflow_guide_tpu.ops import decode_attention as da

    assert "_decode_kernel" in cost._KERNEL_COST_MODELS
    assert "_paged_decode_kernel" in cost._KERNEL_COST_MODELS

    runner = da.make_decode_runner(64, b=2, h=2, s=128, d=64,
                                   dtype=jnp.bfloat16, chunk=1)
    vec = cost.CostVector()
    cost._interpret(jax.make_jaxpr(runner)().jaxpr, vec, mult=1.0,
                    axis_sizes={})
    closed = da.decode_kernel_hbm_bytes(b=2, h=2, s=128, d=64,
                                        dtype=jnp.bfloat16, chunk=8)
    assert vec.hbm_bytes == closed
    assert vec.flops == 4.0 * 2 * 2 * 128 * 8 * 64
