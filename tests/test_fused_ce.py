"""Fused cross-entropy (ops/fused_ce.py) + precision policy (core/precision).

The load-bearing tests are the numerical pins the round-8 issue names:
fused CE must match the naive log_softmax path — loss AND grads — at tp=1
and under vocab parallelism; the fused backward must never materialize a
full (N, V) f32 intermediate (jaxpr-walked, with the naive path as the
positive control for the detector); and the chunk-resolution layer must
stay CPU-hermetic (no autotune table I/O on the cpu backend — PR-2's
hermeticity rule)."""

import json
import os
from pathlib import Path

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from distributed_tensorflow_guide_tpu.core import precision
from distributed_tensorflow_guide_tpu.core.compat import shard_map
from distributed_tensorflow_guide_tpu.core.mesh import MeshSpec, build_mesh
from distributed_tensorflow_guide_tpu.ops import autotune
from distributed_tensorflow_guide_tpu.ops import fused_ce as fce
from distributed_tensorflow_guide_tpu.analysis.walker import (
    max_f32_elems_with_vocab_dim as _max_f32_elems_with_vocab_dim,
)


@pytest.fixture(autouse=True)
def _isolated_table(isolated_autotune_table):
    """Shared isolation (tests/conftest.py): empty in-memory table, tmp
    table file — nothing leaks between tests or to the user cache."""
    yield


def _case(n=24, d=16, v=50, seed=0, dtype=jnp.float32):
    r = np.random.RandomState(seed)
    x = jnp.asarray(r.randn(n, d), jnp.float32).astype(dtype)
    kernel = jnp.asarray(r.randn(d, v) * 0.2, jnp.float32)
    targets = jnp.asarray(r.randint(0, v, (n,)), np.int32)
    return x, kernel, targets


def _naive(x, kernel, targets, reduction="mean"):
    logp = jax.nn.log_softmax(x.astype(jnp.float32) @ kernel)
    ll = jnp.take_along_axis(logp, targets[:, None], axis=-1)[:, 0]
    return -jnp.sum(ll) if reduction == "sum" else -jnp.mean(ll)


# ---- numerical parity -------------------------------------------------------


@pytest.mark.parametrize("chunk", [7, 16, 50, 64])
def test_fused_matches_naive_loss_and_grads(chunk):
    """Loss + BOTH grads match the naive path at every chunking regime:
    ragged tail (7, 16), exactly one chunk (50 = V), chunk > V (clipped)."""
    x, kernel, targets = _case()
    l0, (dx0, dw0) = jax.value_and_grad(
        lambda a, b: _naive(a, b, targets), argnums=(0, 1))(x, kernel)
    l1, (dx1, dw1) = jax.value_and_grad(
        lambda a, b: fce.fused_cross_entropy(a, b, targets, chunk=chunk),
        argnums=(0, 1))(x, kernel)
    np.testing.assert_allclose(l0, l1, rtol=1e-6)
    np.testing.assert_allclose(dx0, dx1, rtol=1e-5, atol=1e-7)
    np.testing.assert_allclose(dw0, dw1, rtol=1e-5, atol=1e-7)


def test_fused_sum_reduction_and_leading_shape():
    """reduction="sum" and (B, S, D)-shaped inputs (the call-site shape)."""
    x, kernel, targets = _case(n=24)
    want = float(_naive(x, kernel, targets, reduction="sum"))
    got = fce.fused_cross_entropy(
        x.reshape(4, 6, -1), kernel, targets.reshape(4, 6),
        chunk=16, reduction="sum")
    np.testing.assert_allclose(want, float(got), rtol=1e-6)


def test_fused_next_token_shift_matches_naive():
    """fused_next_token_loss applies the :-1 / 1: shift the logits-side
    call sites apply — pinned against the explicit spelling."""
    r = np.random.RandomState(1)
    B, S, D, V = 2, 9, 8, 40
    x = jnp.asarray(r.randn(B, S, D), jnp.float32)
    kernel = jnp.asarray(r.randn(D, V) * 0.2, jnp.float32)
    toks = jnp.asarray(r.randint(0, V, (B, S)), np.int32)
    want = _naive(x[:, :-1].reshape(-1, D), kernel,
                  toks[:, 1:].reshape(-1))
    got = fce.fused_next_token_loss(x, kernel, toks, chunk=16)
    np.testing.assert_allclose(float(want), float(got), rtol=1e-6)


def test_fused_bf16_runs_and_keeps_f32_loss():
    """bf16 activations: matmuls in bf16, loss f32, dx back in bf16,
    dW in the kernel's dtype — the precision-policy accumulation
    contract (coarse tolerance: the bf16 matmul IS the diet)."""
    x, kernel, targets = _case(dtype=jnp.bfloat16)
    loss, (dx, dw) = jax.value_and_grad(
        lambda a, b: fce.fused_cross_entropy(a, b, targets, chunk=16),
        argnums=(0, 1))(x, kernel)
    assert loss.dtype == jnp.float32
    assert dx.dtype == jnp.bfloat16 and dw.dtype == kernel.dtype
    l0 = _naive(x.astype(jnp.float32), kernel, targets)
    np.testing.assert_allclose(float(l0), float(loss), rtol=2e-2)


def test_fused_vocab_parallel_matches_naive():
    """The vocab-parallel variant (axis="model"): each device holds a V/8
    kernel shard, the collective triple assembles the loss, the bwd psums
    dx — values AND grads must match the unsharded naive oracle."""
    mesh = build_mesh(MeshSpec(data=1, model=8))
    x, kernel, targets = _case(n=16, d=8, v=64, seed=2)

    def body(x, kernel, targets):
        def loss(x, k):
            return fce.fused_cross_entropy(
                x, k, targets, chunk=4, axis="model")

        l, (dx, dw) = jax.value_and_grad(loss, argnums=(0, 1))(x, kernel)
        return l, dx, dw

    f = jax.jit(shard_map(
        body, mesh=mesh,
        in_specs=(P(), P(None, "model"), P()),
        out_specs=(P(), P(), P(None, "model")),
        check_vma=False,
    ))
    l, dx, dw = f(x, kernel, targets)
    l0, (dx0, dw0) = jax.value_and_grad(
        lambda a, b: _naive(a, b, targets), argnums=(0, 1))(x, kernel)
    np.testing.assert_allclose(float(l0), float(l), rtol=1e-6)
    np.testing.assert_allclose(dx0, dx, rtol=1e-5, atol=1e-7)
    np.testing.assert_allclose(dw0, dw, rtol=1e-5, atol=1e-7)


def test_fused_rejects_bad_args():
    x, kernel, targets = _case()
    with pytest.raises(ValueError, match="reduction"):
        fce.fused_cross_entropy(x, kernel, targets, chunk=8,
                                reduction="max")
    with pytest.raises(ValueError, match="targets shape"):
        fce.fused_cross_entropy(x, kernel, targets[:-1], chunk=8)
    with pytest.raises(ValueError, match="kernel"):
        fce.fused_cross_entropy(x, kernel.T, targets, chunk=8)


# ---- the no-full-logits pin (analysis.walker, ex tests/pin_utils.py) --------


def test_fused_bwd_never_materializes_full_logits():
    """The acceptance-criteria pin: the fused fwd+bwd jaxpr contains NO
    (N, V) f32 intermediate — the largest vocab-dim tensor is one
    (N, chunk) tile. The naive path is the positive control proving the
    detector sees full logits when they exist."""
    n, d, v, chunk = 48, 8, 64, 16
    x, kernel, targets = _case(n=n, d=d, v=v)

    naive_jaxpr = jax.make_jaxpr(jax.grad(
        lambda a, b: _naive(a, b, targets), argnums=(0, 1)))(x, kernel)
    assert _max_f32_elems_with_vocab_dim(naive_jaxpr, n, v) >= n * v

    fused_jaxpr = jax.make_jaxpr(jax.grad(
        lambda a, b: fce.fused_cross_entropy(a, b, targets, chunk=chunk),
        argnums=(0, 1)))(x, kernel)
    assert _max_f32_elems_with_vocab_dim(fused_jaxpr, n, v) == 0
    # ...and the chunk tiles themselves stay at (n, chunk)
    assert _max_f32_elems_with_vocab_dim(fused_jaxpr, n, chunk) <= n * chunk


def test_pipeline_fused_bwd_never_materializes_full_logits():
    """Same pin END TO END: the whole compiled pipeline train step with
    fused_ce=True (chunk 16 < V) has no (mb·(S−1), V) f32 intermediate.
    The config's vocab (80) collides with no other model dimension, so a
    vocab-dim match in the jaxpr can only be a logits-family tensor; the
    fused_ce=False step is the positive control."""
    import optax

    from distributed_tensorflow_guide_tpu.models.transformer import (
        TransformerConfig,
    )
    from distributed_tensorflow_guide_tpu.parallel.pipeline import (
        PipelinedLM,
    )

    cfg = TransformerConfig(
        vocab_size=80, num_layers=2, num_heads=2, d_model=24, d_ff=48,
        max_len=16, causal=True, dtype=jnp.float32)
    mesh = build_mesh(MeshSpec(data=4, pipe=2))
    r = np.random.RandomState(0)
    tokens = r.randint(0, 80, (16, 16)).astype(np.int32)
    n = 2 * (cfg.max_len - 1)  # one microbatch's next-token positions

    def step_jaxpr(fused):
        # fully abstract: make_jaxpr over ShapeDtypeStructs — the pin is a
        # trace property, no device compute or compile needed
        pp = PipelinedLM(mesh, cfg, num_microbatches=2, fused_ce=fused,
                         ce_chunk=16)
        params = jax.eval_shape(pp.init_host_params, jax.random.PRNGKey(0))
        tx = optax.sgd(0.1)
        opt_state = jax.eval_shape(tx.init, params)
        step = pp.make_train_step(tx, params, donate=False)
        return jax.make_jaxpr(step)(opt_state, params, tokens)

    assert _max_f32_elems_with_vocab_dim(
        step_jaxpr(False), n, cfg.vocab_size) >= n * cfg.vocab_size
    assert _max_f32_elems_with_vocab_dim(
        step_jaxpr(True), n, cfg.vocab_size) == 0


# ---- chunk resolution: autotune table + CPU hermeticity ---------------------


def test_ce_chunk_cpu_is_defaults_only_no_table_io():
    """The tier-1 guard the issue names: on the cpu backend the fused-CE
    chunk layer neither reads nor writes the autotune table and refuses
    to sweep — a stray host table must not change what CI traces."""
    path = Path(os.environ["DTG_AUTOTUNE_TABLE"])
    seeded = {autotune._key(autotune.CE_KERNEL, 0, 0, 50304, 768,
                            "bfloat16", False, "cpu"): {"chunk": 1024}}
    path.write_text(json.dumps(seeded))

    got = autotune.ce_chunk_for(n=1024, d=768, v=50304, dtype=jnp.bfloat16)
    assert got == autotune.DEFAULT_CE_CHUNK  # file ignored on cpu
    with pytest.raises(RuntimeError, match="defaults-only"):
        autotune.ce_record(n=1024, d=768, v=50304, dtype=jnp.bfloat16,
                           chunk=2048)
    with pytest.raises(RuntimeError, match="defaults-only"):
        autotune.ensure_ce_tuned(n=1024, d=768, v=50304,
                                 dtype=jnp.bfloat16,
                                 measure=lambda c: 0.0)
    assert json.loads(path.read_text()) == seeded  # file untouched
    # ...and the fused loss itself resolves through the same defaults-only
    # path (no table read) — it must simply run
    x, kernel, targets = _case(v=50)
    float(fce.fused_cross_entropy(x, kernel, targets))


def test_ce_chunk_table_roundtrip_no_resweep():
    """Same key -> same chunk, sweep runs once, persists across a
    simulated restart; vocab-clipping guards stale entries."""
    calls = []

    def measure(chunk):
        calls.append(chunk)
        return 1.0 / chunk  # favors the widest chunk

    kw = dict(n=64, d=16, v=4096, dtype=jnp.float32, platform="tpu")
    first = autotune.ensure_ce_tuned(measure=measure, **kw)
    assert first == 2048  # widest candidate < v
    n_swept = len(calls)
    assert n_swept == len(autotune.ce_chunk_candidates(4096))

    again = autotune.ensure_ce_tuned(measure=measure, **kw)
    assert again == first and len(calls) == n_swept  # no re-sweep

    autotune.reset()  # "restart": reload from the persisted file
    assert autotune.ensure_ce_tuned(measure=measure, **kw) == first
    assert len(calls) == n_swept
    # the N-generic entry serves nearby batch sizes without a sweep
    assert autotune.ce_chunk_for(n=999, d=16, v=4096, dtype=jnp.float32,
                                 platform="tpu") == first
    # a different vocab misses back to the (clipped) default
    assert autotune.ce_chunk_for(n=64, d=16, v=512, dtype=jnp.float32,
                                 platform="tpu") == 512
    with pytest.raises(ValueError, match="invalid"):
        autotune.ce_record(n=64, d=16, v=512, dtype=jnp.float32,
                           chunk=1024, platform="tpu")


def test_resolve_fused_ce_policy():
    assert fce.resolve_fused_ce(True) is True
    assert fce.resolve_fused_ce(False) is False
    assert fce.resolve_fused_ce("on") is True
    assert fce.resolve_fused_ce("off") is False
    # auto: off on cpu (tier-1 traces stay byte-identical) ...
    assert fce.resolve_fused_ce("auto", vocab_size=50304) is False
    # ... on for TPU + chunkable vocab, off for degenerate vocabs
    assert fce.resolve_fused_ce("auto", vocab_size=50304,
                                platform="tpu") is True
    assert fce.resolve_fused_ce("auto", vocab_size=1024,
                                platform="tpu") is False
    with pytest.raises(ValueError, match="fused_ce"):
        fce.resolve_fused_ce("maybe")


# ---- loss-site wiring (flat LM + MoE) ---------------------------------------


def test_make_lm_loss_fn_fused_matches_naive():
    """The DP/FSDP call-site knob: make_lm_loss_fn(fused_ce=True) matches
    the naive loss and grads on the same params."""
    from distributed_tensorflow_guide_tpu.models.transformer import (
        Transformer,
        TransformerConfig,
        make_lm_loss_fn,
    )

    cfg = TransformerConfig(
        vocab_size=64, num_layers=1, num_heads=2, d_model=16, d_ff=32,
        max_len=8, causal=True, dtype=jnp.float32)
    model = Transformer(cfg)
    r = np.random.RandomState(0)
    tokens = jnp.asarray(r.randint(0, 64, (2, 8)), np.int32)
    params = model.init(jax.random.PRNGKey(0), tokens)["params"]
    batch = {"tokens": tokens}

    naive = make_lm_loss_fn(model, fused_ce=False)
    fused = make_lm_loss_fn(model, fused_ce=True, ce_chunk=16)
    (l0, m0), g0 = jax.value_and_grad(naive, has_aux=True)(params, batch)
    (l1, m1), g1 = jax.value_and_grad(fused, has_aux=True)(params, batch)
    np.testing.assert_allclose(float(l0), float(l1), rtol=1e-6)
    np.testing.assert_allclose(float(m0["perplexity"]),
                               float(m1["perplexity"]), rtol=1e-5)
    for a, b in zip(jax.tree.leaves(g0), jax.tree.leaves(g1), strict=True):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-7)


def test_switch_lm_fused_matches_naive():
    """SwitchLM: one fused train step lands on the same loss and params
    as the naive path from identical init (the (se, n) psum assembly is
    shared, so the global mean cannot fork)."""
    import optax

    from distributed_tensorflow_guide_tpu.models.moe_lm import SwitchLM
    from distributed_tensorflow_guide_tpu.models.transformer import (
        TransformerConfig,
    )

    cfg = TransformerConfig(
        vocab_size=64, num_layers=1, num_heads=2, d_model=16, d_ff=32,
        max_len=8, causal=True, dtype=jnp.float32)
    mesh = build_mesh(MeshSpec(data=2, expert=4))
    r = np.random.RandomState(0)
    tokens = jnp.asarray(r.randint(0, 64, (8, 8)), np.int32)

    def run(fused):
        lm = SwitchLM(mesh, cfg, num_experts=4, fused_ce=fused,
                      ce_chunk=16)
        params = lm.init_params(jax.random.PRNGKey(0))
        tx = optax.sgd(0.1)
        opt_state = lm.init_opt_state(tx, params)
        step = lm.make_train_step(tx, params, donate=False)
        opt2, params2, m = step(opt_state, params, tokens)
        return float(m["loss"]), jax.tree.map(np.asarray, params2)

    l0, p0 = run(False)
    l1, p1 = run(True)
    np.testing.assert_allclose(l0, l1, rtol=1e-5)
    for a, b in zip(jax.tree.leaves(p0), jax.tree.leaves(p1), strict=True):
        np.testing.assert_allclose(a, b, rtol=1e-4, atol=1e-6)


# ---- precision policy (core/precision.py) -----------------------------------


def test_precision_presets_and_apply():
    from distributed_tensorflow_guide_tpu.models.transformer import (
        TransformerConfig,
    )

    pol = precision.resolve("bf16_remat_attn")
    assert pol.compute_dtype == jnp.bfloat16
    assert pol.param_dtype == jnp.float32
    assert pol.accum_dtype == jnp.float32
    assert pol.remat == "attention"
    cfg = pol.apply_to_transformer(TransformerConfig())
    assert cfg.dtype == jnp.bfloat16
    assert cfg.resolved_remat_mode == "attention"
    assert cfg.remat is False  # attention mode is NOT full-block remat

    cfg2 = precision.resolve("bf16_remat").apply_to_transformer(
        TransformerConfig())
    assert cfg2.remat is True and cfg2.resolved_remat_mode == "block"
    assert precision.resolve(None).name == "bf16"
    assert precision.resolve(pol) is pol
    assert precision.resolve("fp8").name == "fp8"  # round 21: now a preset
    with pytest.raises(ValueError, match="unknown precision"):
        precision.resolve("fp6")
    with pytest.raises(ValueError, match="remat"):
        precision.Policy("bad", remat="everything")


def test_remat_mode_attention_is_execution_plan_only():
    """remat_mode="attention" must change NOTHING numerically: same loss,
    same grads as no remat (it re-runs the identical attention ops in the
    backward) — and the param layout is unchanged."""
    import dataclasses

    from distributed_tensorflow_guide_tpu.models.transformer import (
        Transformer,
        TransformerConfig,
        make_lm_loss_fn,
    )

    cfg = TransformerConfig(
        vocab_size=64, num_layers=2, num_heads=2, d_model=16, d_ff=32,
        max_len=12, causal=True, dtype=jnp.float32)
    r = np.random.RandomState(0)
    tokens = jnp.asarray(r.randint(0, 64, (4, 12)), np.int32)
    params = Transformer(cfg).init(jax.random.PRNGKey(0), tokens)["params"]

    def run(mode):
        model = Transformer(dataclasses.replace(cfg, remat_mode=mode))
        loss_fn = make_lm_loss_fn(model, fused_ce=False)
        (l, _), g = jax.value_and_grad(loss_fn, has_aux=True)(
            params, {"tokens": tokens})
        return float(l), g

    l0, g0 = run("none")
    l1, g1 = run("attention")
    np.testing.assert_allclose(l0, l1, rtol=1e-6)
    for a, b in zip(jax.tree.leaves(g0), jax.tree.leaves(g1), strict=True):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-5, atol=1e-7)


def test_pipeline_precision_policy_threads_through():
    """PipelinedLM(precision=...) rewrites the config through the policy —
    activation dtype + remat mode — and the step still runs."""
    import optax

    from distributed_tensorflow_guide_tpu.parallel.pipeline import (
        PipelinedLM,
    )
    from tests.test_pipeline import CFG, _tokens

    mesh = build_mesh(MeshSpec(data=4, pipe=2))
    pp = PipelinedLM(mesh, CFG, num_microbatches=2, precision="f32")
    assert pp.cfg.dtype == jnp.float32
    assert pp.cfg.resolved_remat_mode == "none"

    pp2 = PipelinedLM(mesh, CFG, num_microbatches=2,
                      precision="bf16_remat_attn")
    assert pp2.cfg.dtype == jnp.bfloat16
    assert pp2.cfg.resolved_remat_mode == "attention"
    params = pp2.init_params(jax.random.PRNGKey(0))
    tx = optax.sgd(0.1)
    opt_state = pp2.init_opt_state(tx, params)
    step = pp2.make_train_step(tx, params, donate=False)
    _, _, m = step(opt_state, params, _tokens(16))
    assert np.isfinite(float(m["loss"]))
