"""Target script for launcher tests — run only via subprocess, never imported
by pytest (no test_ prefix).

Each launched process initializes from the env the launcher set, psums its
process index across the cluster, and prints a checkable line. With
``--fail-rank K`` process K exits 1 *before* the collective, so the peers
block in it — exercising the launcher's failure-grace supervision.
"""

import argparse
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--fail-rank", type=int, default=-1)
    ns = ap.parse_args()

    from distributed_tensorflow_guide_tpu.core import dist

    dist.initialize()
    import jax
    import jax.numpy as jnp

    if ns.fail_rank >= 0:
        # Supervision scenario: one rank dies, the rest hang in host-side
        # work (immune to the coordination-service death notification that
        # aborts peers blocked in collectives) and must be reaped by grace.
        if jax.process_index() == ns.fail_rank:
            print("injected failure", flush=True)
            # os._exit: an abrupt death (like a segfault/OOM-kill), skipping
            # jax.distributed's atexit shutdown barrier — sys.exit would hang
            # there waiting for the surviving ranks.
            import os
            os._exit(1)
        import time
        time.sleep(300)
        sys.exit(0)

    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
    from jax.experimental.shard_map import shard_map

    mesh = Mesh(jax.devices(), ("data",))
    ranks = jnp.arange(jax.device_count(), dtype=jnp.int32)
    ranks = jax.device_put(ranks, NamedSharding(mesh, P("data")))
    total = jax.jit(
        shard_map(
            lambda x: jax.lax.psum(x, "data"),
            mesh=mesh, in_specs=P("data"), out_specs=P(),
        )
    )(ranks)
    print(
        f"RANKSUM process={jax.process_index()} "
        f"nproc={jax.process_count()} sum={int(total[0])}",
        flush=True,
    )


if __name__ == "__main__":
    main()
