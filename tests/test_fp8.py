"""fp8 across the stack (round 21): the e4m3 storage/training primitives,
the capability gate, and the default-off contracts.

The load-bearing pins:

* ``fp8_ste_dot`` really contracts e4m3 x e4m3 with f32 accumulation and
  its VJP is bit-identical to the unquantized matmul's (the same
  straight-through contract as int8);
* the fp8 levers are EXCLUSIVE (one quantized representation per policy
  / per config) and default OFF — a default-config trace contains no
  float8 dtype anywhere, so round-20 traces are byte-identical;
* ``require_fp8`` refuses pre-fp8 device generations with an actionable
  error (emulated e4m3 costs MORE than bf16), and ``DTG_FP8_EMULATE``
  is the explicit escape for numerics work;
* PRESETS["fp8"] trains the tiny LM against "f32" within a loss band —
  wider than int8's (e4m3 has 3 mantissa bits vs int8's 8-bit grid).
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from distributed_tensorflow_guide_tpu.analysis import walker
from distributed_tensorflow_guide_tpu.core import precision
from distributed_tensorflow_guide_tpu.models.transformer import (
    Transformer,
    TransformerConfig,
)
from distributed_tensorflow_guide_tpu.ops import quant

CFG = TransformerConfig(vocab_size=64, num_layers=2, num_heads=2,
                        d_model=16, d_ff=32, max_len=64, causal=True,
                        dtype=jnp.float32)


# ---- storage-side primitives ------------------------------------------------


def test_quantize_channelwise_fp8_roundtrip():
    rng = np.random.RandomState(0)
    w = jnp.asarray(rng.randn(32, 8).astype(np.float32))
    q, scale = quant.quantize_channelwise(w, bits="fp8")
    assert q.dtype == quant.FP8_DTYPE and scale.shape == (8,)
    deq = q.astype(jnp.float32) * scale[None, :]
    # e4m3 keeps 3 mantissa bits: worst-case relative step ~2^-3 on the
    # stored value, so pin a per-column bound scaled by the column max
    err = np.max(np.abs(np.asarray(deq - w)), axis=0)
    colmax = np.max(np.abs(np.asarray(w)), axis=0)
    assert np.all(err <= colmax * 0.0725)


def test_wq_matmul_fp8_matches_unfused_oracle():
    rng = np.random.RandomState(1)
    x = jnp.asarray(rng.randn(4, 32).astype(np.float32))
    w = jnp.asarray(rng.randn(32, 8).astype(np.float32))
    q, scale = quant.quantize_channelwise(w, bits="fp8")
    got = quant.wq_matmul(x, q, scale, bits="fp8")
    oracle = x @ (q.astype(jnp.float32) * scale[None, :])
    np.testing.assert_allclose(np.asarray(got), np.asarray(oracle),
                               rtol=1e-5, atol=1e-5)


def test_check_bits_error_names_fp8():
    with pytest.raises(ValueError, match="fp8"):
        quant.quantize_channelwise(jnp.ones((4, 4)), bits=3)


def test_fp8_ste_dot_contracts_e4m3_and_grads_are_straight_through():
    """The trace really contains an e4m3 x e4m3 -> f32 contraction (the
    mode rules.py's fp8 gate legalizes), the forward stays within the
    two-operand quantization bound, and the VJP is bit-identical to the
    unquantized matmul's — the same straight-through contract as int8."""
    rng = np.random.RandomState(4)
    x = jnp.asarray(rng.randn(4, 16).astype(np.float32))
    w = jnp.asarray(rng.randn(16, 8).astype(np.float32))
    jx = jax.make_jaxpr(quant.fp8_ste_dot)(x, w)
    dots = [e for e in walker.walk(jx.jaxpr)
            if e.primitive.name == "dot_general"]
    assert [str(v.aval.dtype) for v in dots[0].invars] == [
        "float8_e4m3fn", "float8_e4m3fn"]
    assert str(dots[0].outvars[0].aval.dtype) == "float32"

    ref = x @ w
    rel = float(jnp.max(jnp.abs(quant.fp8_ste_dot(x, w) - ref))
                / jnp.max(jnp.abs(ref)))
    assert rel < 0.15  # two e4m3 operands: ~2x the 3-mantissa-bit step

    _, vjp_q = jax.vjp(quant.fp8_ste_dot, x, w)
    _, vjp_f = jax.vjp(lambda a, b: a @ b, x, w)
    ct = jnp.asarray(rng.randn(4, 8).astype(np.float32))
    for got, want in zip(vjp_q(ct), vjp_f(ct)):
        assert np.array_equal(np.asarray(got), np.asarray(want))


# ---- policy / config contracts ----------------------------------------------


def test_policy_fp8_preset_and_exclusivity():
    pol = precision.resolve("fp8")
    assert pol.fp8_matmuls and not pol.quantized_matmuls
    assert pol.compute_dtype == jnp.float32  # int8-style isolation
    with pytest.raises(ValueError, match="exclusive"):
        precision.Policy("both", quantized_matmuls=True, fp8_matmuls=True)


def test_config_fp8_exclusions():
    with pytest.raises(ValueError, match="exclusive"):
        dataclasses.replace(CFG, fp8_matmuls=True, quantized_matmuls=True)
    with pytest.raises(ValueError):
        dataclasses.replace(CFG, fp8_matmuls=True, weight_dtype="fp8")
    with pytest.raises(ValueError, match="weight_dtype"):
        dataclasses.replace(CFG, weight_dtype="e5m2")
    # each lever alone is a valid config
    dataclasses.replace(CFG, fp8_matmuls=True)
    dataclasses.replace(CFG, weight_dtype="fp8")


def test_fp8_off_default_trace_has_no_float8():
    """Default-off means OFF: a default-config trace contains no float8
    dtype anywhere — which is why landing fp8 blessed zero existing
    golden fingerprints (round-20 traces stay byte-identical)."""
    assert CFG.fp8_matmuls is False and CFG.weight_dtype is None
    model = Transformer(CFG)
    x = jnp.zeros((2, 8), jnp.int32)
    prm = model.init(jax.random.PRNGKey(0), x)["params"]
    jx = jax.make_jaxpr(lambda p: model.apply({"params": p}, x))(prm)
    assert "f8" not in str(jx)


# ---- capability gate --------------------------------------------------------


def test_fp8_capability_by_device_kind(monkeypatch):
    monkeypatch.delenv(precision.FP8_EMULATE_ENV, raising=False)
    assert precision.fp8_capable("TPU v6e")
    assert precision.fp8_capable("TPU v7x")
    assert not precision.fp8_capable("TPU v5 lite")
    assert not precision.fp8_capable("TPU v4")
    assert not precision.fp8_capable("cpu")


def test_require_fp8_refuses_with_actionable_error(monkeypatch):
    monkeypatch.delenv(precision.FP8_EMULATE_ENV, raising=False)
    with pytest.raises(ValueError) as ei:
        precision.require_fp8("TPU v5 lite")
    msg = str(ei.value)
    # the error must tell the user WHY (emulation is a net loss) and
    # WHAT to do instead (bf16/int8, or the explicit emulation env)
    assert "emulate" in msg and "bf16" in msg
    assert precision.FP8_EMULATE_ENV in msg
    precision.require_fp8("TPU v6e")  # capable kind passes


def test_fp8_emulate_env_escape(monkeypatch):
    monkeypatch.setenv(precision.FP8_EMULATE_ENV, "1")
    assert precision.fp8_capable("cpu")
    precision.require_fp8("TPU v4")  # no raise under the escape hatch
    monkeypatch.setenv(precision.FP8_EMULATE_ENV, "0")
    assert not precision.fp8_capable("cpu")


# ---- training parity --------------------------------------------------------


def test_fp8_policy_loss_parity_with_f32():
    """PRESETS["fp8"] trains the tiny LM step-for-step against "f32" —
    same f32 masters, same everything except the projection contraction
    representation (the int8 parity pin's geometry, wider band: e4m3's
    3 mantissa bits are coarser than the int8 grid)."""
    small = dataclasses.replace(CFG, max_len=32)

    def train(cfg, steps=5):
        model = Transformer(cfg)
        prm = model.init(jax.random.PRNGKey(0),
                         jnp.zeros((2, 8), jnp.int32))["params"]
        tx = optax.adam(1e-2)
        opt = tx.init(prm)
        xs = np.random.RandomState(0).randint(
            0, cfg.vocab_size, (steps, 4, 8)).astype(np.int32)

        @jax.jit
        def step(prm, opt, x):
            def loss_fn(p):
                lp = jax.nn.log_softmax(
                    model.apply({"params": p}, x[:, :-1]), -1)
                return -jnp.mean(jnp.take_along_axis(
                    lp, x[:, 1:, None], -1))

            loss, g = jax.value_and_grad(loss_fn)(prm)
            up, opt = tx.update(g, opt, prm)
            return optax.apply_updates(prm, up), opt, loss

        out = []
        for x in xs:
            prm, opt, loss = step(prm, opt, x)
            out.append(float(loss))
        return out

    l_f32 = train(precision.PRESETS["f32"].apply_to_transformer(small))
    l_fp8 = train(precision.PRESETS["fp8"].apply_to_transformer(small))
    for a, b in zip(l_f32, l_fp8):
        assert abs(a - b) / a < 5e-2
