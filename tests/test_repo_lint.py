"""Repo-wide source hygiene sweep, promoted to tier-1.

Round 8 ran an ad-hoc AST pass over the package to catch unused imports
before shipping; it caught real ones, then evaporated with the session.
This file is that sweep as a permanent test — ruff is config-only in
this container, so the two checks it would give us for free are done by
hand on the stdlib ``ast``:

* **unused imports** — an ``import x`` / ``from m import x`` whose bound
  name is never read anywhere in the module (attribute roots count, and
  names re-exported via ``__all__`` or ``# noqa`` lines are exempt).
* **shadowed stdlib names** — a module file whose basename collides with
  a stdlib top-level module it (or a sibling) imports. Python 3's
  absolute imports make the collision survivable until someone runs the
  file as a script or adds the package dir to ``sys.path`` — at which
  point ``import types`` quietly resolves to our file. Cheaper to ban.

The walk covers the package, ``benchmarks/``, ``tests/`` and the
repo-root scripts; findings name file, line and symbol so the failure
is actionable without re-running anything locally.
"""

from __future__ import annotations

import ast
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parents[1]

SCAN_DIRS = ("distributed_tensorflow_guide_tpu", "benchmarks", "tests")
SCAN_ROOT_GLOBS = ("*.py",)

#: Imports whose *side effect* is the point — module registration,
#: backend setup — keyed on the exact dotted module spelled in the
#: import statement. Bound-but-unread is correct for these.
SIDE_EFFECT_IMPORTS = frozenset({
    "distributed_tensorflow_guide_tpu.analysis.programs",
})


def _py_files() -> list[Path]:
    files: list[Path] = []
    for d in SCAN_DIRS:
        files.extend(sorted((REPO / d).rglob("*.py")))
    for g in SCAN_ROOT_GLOBS:
        files.extend(sorted(REPO.glob(g)))
    return [f for f in files if "__pycache__" not in f.parts]


def _noqa_lines(src: str) -> set[int]:
    return {i for i, line in enumerate(src.splitlines(), 1)
            if "# noqa" in line}


class _ImportVisitor(ast.NodeVisitor):
    """Collect (binding name, lineno, dotted module) per import, and every
    name READ anywhere (loads, attribute roots, decorators, strings in
    ``__all__`` handled separately)."""

    def __init__(self) -> None:
        # (name, statement line, alias line, dotted module) — noqa on
        # EITHER line exempts (a shim puts one noqa on the `from (` line
        # to cover its whole re-export list)
        self.bound: list[tuple[str, int, int, str]] = []
        self.read: set[str] = set()

    def visit_Import(self, node: ast.Import) -> None:
        for alias in node.names:
            name = alias.asname or alias.name.split(".")[0]
            self.bound.append((name, node.lineno, node.lineno, alias.name))

    def visit_ImportFrom(self, node: ast.ImportFrom) -> None:
        for alias in node.names:
            if alias.name == "*":
                continue
            name = alias.asname or alias.name
            dotted = f"{node.module or ''}.{alias.name}"
            self.bound.append(
                (name, node.lineno, getattr(alias, "lineno", node.lineno),
                 dotted))

    def visit_Name(self, node: ast.Name) -> None:
        # Del counts as a reference: `import jax; ...; del jax` is the
        # documented import-for-side-effect-then-discard idiom
        if isinstance(node.ctx, (ast.Load, ast.Del)):
            self.read.add(node.id)
        self.generic_visit(node)

    def visit_Attribute(self, node: ast.Attribute) -> None:
        # the chain root is a Name; generic_visit reaches it
        self.generic_visit(node)

    def harvest_string_annotations(self, tree: ast.Module) -> None:
        """String annotations (`x: "Any"`) reference names invisibly to
        the Name visitor; parse the strings found in annotation slots
        only (an arbitrary string literal elsewhere must NOT exempt an
        import that happens to share its spelling)."""
        anns: list[ast.expr] = []
        for node in ast.walk(tree):
            if isinstance(node, ast.AnnAssign):
                anns.append(node.annotation)
            elif isinstance(node, ast.arg) and node.annotation is not None:
                anns.append(node.annotation)
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                if node.returns is not None:
                    anns.append(node.returns)
        for ann in anns:
            for c in ast.walk(ann):
                if isinstance(c, ast.Constant) and isinstance(c.value, str):
                    try:
                        sub = ast.parse(c.value, mode="eval")
                    except SyntaxError:
                        continue
                    for n in ast.walk(sub):
                        if isinstance(n, ast.Name):
                            self.read.add(n.id)


def _exported_names(tree: ast.Module) -> set[str]:
    out: set[str] = set()
    for node in tree.body:
        targets = []
        if isinstance(node, ast.Assign):
            targets = node.targets
        elif isinstance(node, ast.AugAssign):
            targets = [node.target]
        for t in targets:
            if isinstance(t, ast.Name) and t.id == "__all__":
                for el in ast.walk(node.value):
                    if isinstance(el, ast.Constant) and isinstance(
                            el.value, str):
                        out.add(el.value)
    return out


def _unused_imports(path: Path) -> list[str]:
    src = path.read_text()
    tree = ast.parse(src)
    v = _ImportVisitor()
    v.visit(tree)
    v.harvest_string_annotations(tree)
    noqa = _noqa_lines(src)
    exported = _exported_names(tree)
    is_dunder_init = path.name == "__init__.py"
    findings = []
    for name, stmt_line, lineno, dotted in v.bound:
        if (name in v.read or name in exported
                or lineno in noqa or stmt_line in noqa):
            continue
        if dotted in SIDE_EFFECT_IMPORTS:
            continue
        if is_dunder_init:
            # package __init__ imports ARE the public re-export surface
            continue
        if name == "annotations" and dotted.startswith("__future__"):
            continue
        shown = path.relative_to(REPO) if REPO in path.parents else path
        findings.append(f"{shown}:{lineno}: unused import '{name}'")
    return findings


def test_no_unused_imports():
    findings: list[str] = []
    for f in _py_files():
        findings.extend(_unused_imports(f))
    assert not findings, "unused imports:\n" + "\n".join(findings)


def test_no_stdlib_shadowing_module_names():
    stdlib = set(getattr(sys, "stdlib_module_names", ()))
    findings = []
    for f in _py_files():
        stem = f.stem
        if stem in ("__init__", "__main__"):
            continue
        if stem in stdlib:
            findings.append(
                f"{f.relative_to(REPO)}: module name '{stem}' shadows the "
                f"stdlib module of the same name")
    assert not findings, "stdlib-shadowing module names:\n" + "\n".join(
        findings)


def test_sweep_catches_a_planted_unused_import(tmp_path):
    """Positive control: the sweep is only trustworthy if a known-bad file
    actually trips it."""
    bad = tmp_path / "planted.py"
    bad.write_text("import os\nimport json\nprint(json.dumps({}))\n")
    findings = _unused_imports(bad)
    assert len(findings) == 1 and "unused import 'os'" in findings[0]


def test_sweep_respects_noqa_and_dunder_all(tmp_path):
    ok = tmp_path / "fine.py"
    ok.write_text(
        "import os  # noqa: F401\n"
        "from json import dumps\n"
        "__all__ = ['dumps']\n")
    assert _unused_imports(ok) == []
