"""Online in-situ autotuning (round 21): the ``ensure_tuned_online``
front door in ops/autotune.py.

The three hard bounds from the module contract, each pinned here:

* **default-off**: with ``DTG_ONLINE_TUNE`` unset nothing sweeps, even
  on a tpu-platform key with a measure injected;
* **CPU-hermetic**: with the env SET, the cpu platform is bitwise the
  fallback path — no sweep runs, no table file appears, the attempted
  counter stays zero (so CPU tier-1 can run with the env exported and
  stay byte-identical to a run without it);
* **bounded + once**: a first-touch key sweeps ONCE and persists
  through the crash-safe table (a simulated restart serves it as a
  lookup hit), a zero budget blocks all sweeps, and a key whose sweep
  RAISES is marked attempted and never retried (serving loops must not
  re-pay a failing sweep).

The sweep mechanism runs with an INJECTED measure function and platform
forced to "tpu" — the same discipline as tests/test_autotune.py.
"""

import os
from pathlib import Path

import jax.numpy as jnp
import pytest

from distributed_tensorflow_guide_tpu.ops import autotune
from distributed_tensorflow_guide_tpu.ops import decode_attention as DA


@pytest.fixture(autouse=True)
def _isolated_table(isolated_autotune_table):
    yield


KW = dict(b=1, h=1, s=256, d=64, dtype=jnp.float32)


def _table_file() -> Path:
    return Path(os.environ["DTG_AUTOTUNE_TABLE"])


def _spy():
    calls = []

    def measure(kernel, blocks):
        calls.append(blocks)
        return 1.0 / (blocks[0] * blocks[1])  # favors the largest blocks

    return calls, measure


def test_default_off_no_sweep_even_on_tpu_keys(monkeypatch):
    monkeypatch.delenv("DTG_ONLINE_TUNE", raising=False)
    calls, measure = _spy()
    out = autotune.ensure_tuned_online("flash_fwd", measure=measure,
                                       platform="tpu", **KW)
    assert out == autotune.blocks_for("flash_fwd", platform="tpu", **KW)
    assert calls == []
    assert autotune.online_tune_stats()["attempted"] == 0


def test_cpu_hermetic_with_env_set(monkeypatch):
    """The tier-1 contract: exporting DTG_ONLINE_TUNE must not change a
    single byte of CPU behavior — no sweeps, no table I/O, and every
    resolver returns exactly its fallback."""
    monkeypatch.setenv("DTG_ONLINE_TUNE", "1")
    assert autotune.online_tune_enabled()
    calls, measure = _spy()

    # flash family through the front door (platform resolves to cpu)
    out = autotune.ensure_tuned_online("flash_fwd", measure=measure, **KW)
    assert out == autotune.blocks_for("flash_fwd", **KW)
    # CE chunk and DP bucket families
    ce = autotune.ensure_tuned_online(
        autotune.CE_KERNEL, measure=measure, n=128, d=64, v=256,
        dtype=jnp.float32)
    assert ce == autotune.ce_chunk_for(n=128, d=64, v=256,
                                       dtype=jnp.float32)
    # the real decode call sites (the serving hot path)
    blk = DA.decode_blk_k_for(b=1, h=2, s=256, d=64, dtype=jnp.float32)
    assert 256 % blk == 0
    pblk = DA.paged_decode_blk_k_for(b=1, h=2, s=256, d=64,
                                     dtype=jnp.float32, block_size=64)
    assert 64 % pblk == 0

    assert calls == []
    assert not _table_file().exists()
    assert autotune.online_tune_stats()["attempted"] == 0


def test_online_sweep_once_persists_then_lookup_hits(monkeypatch):
    monkeypatch.setenv("DTG_ONLINE_TUNE", "1")
    calls, measure = _spy()
    first = autotune.ensure_tuned_online("flash_fwd", measure=measure,
                                         platform="tpu", **KW)
    assert first == (256, 256)
    n_swept = len(calls)
    assert n_swept == len(autotune.candidate_blocks(
        "flash_fwd", s=KW["s"], d=KW["d"], dtype=jnp.float32))
    assert _table_file().exists()

    again = autotune.ensure_tuned_online("flash_fwd", measure=measure,
                                         platform="tpu", **KW)
    assert again == first and len(calls) == n_swept  # no re-sweep

    stats = autotune.online_tune_stats()
    assert stats["attempted"] == 1
    assert 0 <= stats["spent_s"] <= stats["budget_s"]

    # "restart": in-memory state dropped, the persisted entry serves the
    # key as a lookup hit — still no second sweep
    autotune.reset()
    reloaded = autotune.ensure_tuned_online("flash_fwd", measure=measure,
                                            platform="tpu", **KW)
    assert reloaded == first and len(calls) == n_swept
    assert autotune.online_tune_stats()["attempted"] == 0  # hit, not try


def test_zero_budget_blocks_all_sweeps(monkeypatch):
    monkeypatch.setenv("DTG_ONLINE_TUNE", "1")
    monkeypatch.setenv("DTG_ONLINE_TUNE_BUDGET_S", "0")
    calls, measure = _spy()
    out = autotune.ensure_tuned_online("flash_fwd", measure=measure,
                                       platform="tpu", **KW)
    assert out == autotune.blocks_for("flash_fwd", platform="tpu", **KW)
    assert calls == [] and not _table_file().exists()


def test_failed_sweep_marks_attempted_never_retries(monkeypatch):
    """A sweep whose every candidate fails (per-candidate isolation in
    ensure_tuned tries each once) resolves to the fallback, and the key
    is marked attempted — the NEXT resolution calls no measure at all, a
    serving loop never re-pays a failing sweep."""
    monkeypatch.setenv("DTG_ONLINE_TUNE", "1")
    calls = []

    def measure(kernel, blocks):
        calls.append(blocks)
        raise RuntimeError("chip flaked mid-sweep")

    fallback = autotune.blocks_for("flash_fwd", platform="tpu", **KW)
    n_cands = len(autotune.candidate_blocks(
        "flash_fwd", s=KW["s"], d=KW["d"], dtype=jnp.float32))
    first = autotune.ensure_tuned_online("flash_fwd", measure=measure,
                                         platform="tpu", **KW)
    assert first == fallback and len(calls) == n_cands
    again = autotune.ensure_tuned_online("flash_fwd", measure=measure,
                                         platform="tpu", **KW)
    assert again == fallback and len(calls) == n_cands  # attempted-once
    assert autotune.online_tune_stats()["attempted"] == 1


def test_bucket_kernel_needs_measure_even_when_enabled(monkeypatch):
    """The bucket family has no self-contained runner: without a caller-
    supplied measure the front door must resolve to the default, not
    attempt anything."""
    monkeypatch.setenv("DTG_ONLINE_TUNE", "1")
    key = dict(param_bytes=1 << 20, world=8, dtype=jnp.float32)
    out = autotune.ensure_tuned_online(autotune.BUCKET_KERNEL,
                                       platform="tpu", **key)
    assert out == autotune.bucket_bytes_for(platform="tpu", **key)
    assert autotune.online_tune_stats()["attempted"] == 0


def test_set_online_tune_override_wins_over_env(monkeypatch):
    monkeypatch.delenv("DTG_ONLINE_TUNE", raising=False)
    assert not autotune.online_tune_enabled()
    prev = autotune.set_online_tune(True)
    assert prev is None and autotune.online_tune_enabled()
    monkeypatch.setenv("DTG_ONLINE_TUNE", "1")
    autotune.set_online_tune(False)
    assert not autotune.online_tune_enabled()  # override beats truthy env
    autotune.set_online_tune(None)
    assert autotune.online_tune_enabled()  # cleared -> env gate again


def test_decode_kernels_require_explicit_fallback(monkeypatch):
    monkeypatch.delenv("DTG_ONLINE_TUNE", raising=False)
    for kernel in (autotune.DECODE_KERNEL, autotune.PAGED_DECODE_KERNEL):
        with pytest.raises(ValueError, match="fallback"):
            autotune.ensure_tuned_online(kernel, b=1, h=2, s=256, d=64,
                                         dtype=jnp.float32, causal=False)
