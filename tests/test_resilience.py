"""Resilience layer: async+verified checkpointing, restore ladder, startup
hygiene (docs/resilience.md). The chaos-driven end-to-end pins live in
tests/test_chaos.py; these are the unit contracts."""

import json
import logging

import jax.numpy as jnp
import numpy as np
import pytest

from distributed_tensorflow_guide_tpu.testing.chaos import corrupt_checkpoint
from distributed_tensorflow_guide_tpu.train.checkpoint import (
    Checkpointer,
    CheckpointHook,
    LayoutMismatchError,
)
from distributed_tensorflow_guide_tpu.train.hooks import StopAtStepHook
from distributed_tensorflow_guide_tpu.train.loop import TrainLoop


def _state(scale=1.0):
    return {"params": jnp.full((64,), float(scale)),
            "opt": jnp.zeros((64,))}


# ---- async save + commit barrier -------------------------------------------


def test_async_save_defers_manifest_to_barrier(tmp_path):
    d = tmp_path / "ck"
    ckpt = Checkpointer(d)
    assert ckpt.save(1, _state(), async_=True)
    # the commit marker must NOT exist before a barrier: an async save that
    # looked durable before its background write finished would defeat the
    # whole integrity contract
    assert not (d / "manifest_1.json").exists()
    ckpt.wait()  # the explicit barrier
    assert (d / "manifest_1.json").exists()
    assert ckpt.verify_step(1)
    restored = ckpt.restore(_state(0.0))
    np.testing.assert_array_equal(np.asarray(restored["params"]),
                                  np.asarray(_state()["params"]))
    ckpt.close()


def test_async_save_commits_at_next_save(tmp_path):
    d = tmp_path / "ck"
    ckpt = Checkpointer(d, max_to_keep=5)
    ckpt.save(1, _state(1), async_=True)
    ckpt.save(2, _state(2), async_=True)  # barrier for step 1 runs first
    assert (d / "manifest_1.json").exists()
    ckpt.close()  # close is also a barrier: commits step 2
    assert (d / "manifest_2.json").exists()


def test_sync_save_commits_immediately(tmp_path):
    d = tmp_path / "ck"
    ckpt = Checkpointer(d)
    ckpt.save(3, _state())
    assert (d / "manifest_3.json").exists()
    man = json.loads((d / "manifest_3.json").read_text())
    assert man["step"] == 3 and man["files"]  # per-file [size, crc] entries
    assert all(len(v) == 2 for v in man["files"].values())
    ckpt.close()


def test_latest_step_is_a_barrier(tmp_path):
    ckpt = Checkpointer(tmp_path / "ck")
    ckpt.save(4, _state(), async_=True)
    assert ckpt.latest_step() == 4  # drains + commits the pending save
    assert (tmp_path / "ck" / "manifest_4.json").exists()
    ckpt.close()


def test_async_restore_roundtrip_bitwise(tmp_path):
    """An async-saved checkpoint restores bitwise-identical — the snapshot
    happens at save() time, so later mutations of the live state must not
    leak into the written checkpoint."""
    ckpt = Checkpointer(tmp_path / "ck")
    state = {"w": np.arange(1024, dtype=np.float32)}
    ckpt.save(1, state, async_=True)
    state["w"] += 777.0  # mutate AFTER save returned, BEFORE the barrier
    restored = ckpt.restore({"w": np.zeros(1024, np.float32)})
    np.testing.assert_array_equal(restored["w"],
                                  np.arange(1024, dtype=np.float32))
    ckpt.close()


# ---- integrity manifest -----------------------------------------------------


def test_verify_step_catches_truncation(tmp_path):
    ckpt = Checkpointer(tmp_path / "ck")
    ckpt.save(1, _state())
    assert ckpt.verify_step(1)
    corrupt_checkpoint(tmp_path / "ck", mode="truncate")
    assert not ckpt.verify_step(1)
    ckpt.close()


def test_verify_step_catches_same_size_bitflip(tmp_path):
    """A flip keeps the file size — only the CRC32 in the manifest can see
    it. This is the case a size-only check would wave through."""
    ckpt = Checkpointer(tmp_path / "ck")
    ckpt.save(1, _state())
    step, rel = corrupt_checkpoint(tmp_path / "ck", mode="flip")
    assert (tmp_path / "ck" / "1" / rel).stat().st_size == \
        json.loads((tmp_path / "ck" / "manifest_1.json").read_text())[
            "files"][rel][0]
    assert not ckpt.verify_step(1)
    ckpt.close()


def test_manifest_gcd_with_max_to_keep(tmp_path):
    """Satellite: max_to_keep accounting stays correct — manifests (like
    layout sidecars) are GC'd with their step, so a reused step number in a
    later run can never inherit a stale manifest."""
    d = tmp_path / "ck"
    ckpt = Checkpointer(d, max_to_keep=2)
    for s in (1, 2, 3, 4):
        ckpt.save(s, _state(s))
    assert ckpt.all_steps() == [3, 4]
    manifests = sorted(p.name for p in d.glob("manifest_*.json"))
    assert manifests == ["manifest_3.json", "manifest_4.json"]
    ckpt.close()


# ---- restore ladder ---------------------------------------------------------


def test_restore_ladder_falls_back_and_logs_skipped(tmp_path, caplog):
    d = tmp_path / "ck"
    ckpt = Checkpointer(d, max_to_keep=4)
    ckpt.save(5, _state(5))
    ckpt.save(10, _state(10))
    corrupt_checkpoint(d, step=10, mode="truncate")
    with caplog.at_level(logging.WARNING, logger="dtg.train"):
        state, step = ckpt.restore_latest_valid(_state(0))
    assert step == 5
    np.testing.assert_array_equal(np.asarray(state["params"]),
                                  np.asarray(_state(5)["params"]))
    # the fallback is logged WITH the skipped step numbers (acceptance)
    assert any("restore ladder" in r.getMessage() and "[10]" in r.getMessage()
               for r in caplog.records)
    ckpt.close()


def test_restore_ladder_all_corrupt_returns_none(tmp_path):
    d = tmp_path / "ck"
    ckpt = Checkpointer(d)
    ckpt.save(5, _state(5))
    corrupt_checkpoint(d, step=5, mode="flip")
    assert ckpt.restore_latest_valid(_state(0)) is None
    ckpt.close()


def test_restore_ladder_catches_unmanifested_corruption(tmp_path):
    """A checkpoint with no manifest (older writer) that fails to RESTORE
    still falls down the ladder — the try/except half of the contract."""
    d = tmp_path / "ck"
    ckpt = Checkpointer(d, max_to_keep=4)
    ckpt.save(5, _state(5))
    ckpt.save(10, _state(10))
    (d / "manifest_10.json").unlink()  # simulate a pre-manifest save
    corrupt_checkpoint(d, step=10, mode="truncate")
    assert ckpt.verify_step(10)  # unverifiable -> passes verification...
    state, step = ckpt.restore_latest_valid(_state(0))  # ...restore catches
    assert step == 5
    ckpt.close()


def test_restore_ladder_reraises_layout_mismatch(tmp_path):
    """A layout mismatch is a CONFIGURATION error, not corruption: silently
    laddering past it would restore a older checkpoint into the wrong
    model shape story. It must raise."""
    d = tmp_path / "ck"
    ckpt = Checkpointer(d, default_layout={"schedule": "gpipe", "P": 2})
    ckpt.save(1, _state())
    ckpt.close()
    other = Checkpointer(d, default_layout={"schedule": "1f1b", "P": 4})
    with pytest.raises(LayoutMismatchError):
        other.restore_latest_valid(_state(0))
    other.close()


def test_restore_latest_valid_empty_dir_returns_none(tmp_path):
    ckpt = Checkpointer(tmp_path / "empty")
    assert ckpt.restore_latest_valid(_state(0)) is None
    ckpt.close()


# ---- startup hygiene --------------------------------------------------------


def test_init_cleans_stale_orbax_tmp_dirs(tmp_path):
    """Satellite: a kill mid-save leaves a ``<step>.orbax-checkpoint-tmp-*``
    dir (orbax's atomic-rename commit never happened) plus possibly a
    half-written manifest tmp. A fresh Checkpointer must sweep both."""
    d = tmp_path / "ck"
    ckpt = Checkpointer(d)
    ckpt.save(1, _state())
    ckpt.close()
    # simulate the partial write a SIGKILL mid-save leaves behind
    tmp_dir = d / "7.orbax-checkpoint-tmp-123456"
    (tmp_dir / "default").mkdir(parents=True)
    (tmp_dir / "default" / "junk").write_bytes(b"\0" * 512)
    (d / "manifest_7.json.tmp").write_text("{\"step\": 7")
    ckpt2 = Checkpointer(d)
    assert not tmp_dir.exists()
    assert not (d / "manifest_7.json.tmp").exists()
    assert sorted(ckpt2.cleaned_on_start) == [
        "7.orbax-checkpoint-tmp-123456", "manifest_7.json.tmp"]
    # the committed checkpoint survived the sweep and still verifies
    assert ckpt2.latest_step() == 1 and ckpt2.verify_step(1)
    ckpt2.close()


def test_clean_start_reports_nothing(tmp_path):
    ckpt = Checkpointer(tmp_path / "ck")
    assert ckpt.cleaned_on_start == []
    ckpt.close()


# ---- CheckpointHook async mode ---------------------------------------------


def _count_step(state, batch):
    return {"w": state["w"] + batch}, {"loss": jnp.sum(state["w"])}


def _run_hook_loop(tmpdir, async_save):
    ckpt = Checkpointer(tmpdir, max_to_keep=10)
    loop = TrainLoop(
        _count_step, {"w": jnp.zeros((32,))},
        (jnp.full((32,), float(s)) for s in range(1000)),
        hooks=[StopAtStepHook(9),
               CheckpointHook(ckpt, every_steps=2, async_save=async_save)],
    )
    final = loop.run()
    ckpt.wait()
    steps = ckpt.all_steps()
    valid = [s for s in steps if ckpt.verify_step(s)]
    restored = ckpt.restore(final, step=max(steps))
    ckpt.close()
    return final, steps, valid, restored


def test_checkpoint_hook_async_parity_with_sync(tmp_path):
    """async_save=True must change WHEN durability settles, never WHAT is
    saved: same checkpoint labels, every save verifies, final restored
    state bitwise-equal to the sync hook's."""
    f_sync, steps_sync, valid_sync, r_sync = _run_hook_loop(
        tmp_path / "sync", async_save=False)
    f_async, steps_async, valid_async, r_async = _run_hook_loop(
        tmp_path / "async", async_save=True)
    assert steps_sync == steps_async == valid_sync == valid_async
    np.testing.assert_array_equal(np.asarray(r_sync["w"]),
                                  np.asarray(r_async["w"]))
    np.testing.assert_array_equal(np.asarray(f_sync["w"]),
                                  np.asarray(f_async["w"]))
