import json

import jax.numpy as jnp

from distributed_tensorflow_guide_tpu.train import (
    LoggingHook,
    MetricsJSONLHook,
    StepCounterHook,
    StopAtStepHook,
    TrainLoop,
)


def _toy_step(state, batch):
    return state + batch, {"loss": jnp.asarray(1.0 / (state + 1.0))}


def _ones():
    while True:
        yield 1.0


def test_stop_at_step():
    loop = TrainLoop(_toy_step, 0.0, _ones(), hooks=[StopAtStepHook(5)])
    final = loop.run()
    assert loop.step == 5
    assert final == 5.0


def test_data_exhaustion_stops_loop():
    loop = TrainLoop(_toy_step, 0.0, [1.0, 1.0, 1.0])
    final = loop.run()
    assert loop.step == 3 and final == 3.0


def test_metrics_jsonl(tmp_path):
    path = tmp_path / "metrics.jsonl"
    loop = TrainLoop(
        _toy_step,
        0.0,
        _ones(),
        hooks=[StopAtStepHook(4), MetricsJSONLHook(path, every_steps=2)],
    )
    loop.run()
    recs = [json.loads(l) for l in path.read_text().splitlines()]
    assert [r["step"] for r in recs] == [0, 2]
    assert abs(recs[1]["loss"] - 1.0 / 3.0) < 1e-6


def test_step_counter_measures():
    h = StepCounterHook(every_steps=2, batch_size=8, n_chips=2)
    loop = TrainLoop(_toy_step, 0.0, _ones(), hooks=[StopAtStepHook(7), h])
    loop.run()
    assert h.last_steps_per_sec is not None and h.last_steps_per_sec > 0
    assert h.last_examples_per_sec_per_chip == h.last_steps_per_sec * 4


def test_logging_hook_runs(caplog):
    import logging

    with caplog.at_level(logging.INFO, logger="dtg.train"):
        TrainLoop(
            _toy_step, 0.0, _ones(), hooks=[StopAtStepHook(3), LoggingHook(1)]
        ).run()
    assert any("loss=" in r.message for r in caplog.records)


def test_start_step_resume_semantics():
    loop = TrainLoop(_toy_step, 0.0, _ones(), hooks=[StopAtStepHook(10)], start_step=7)
    loop.run()
    assert loop.step == 10  # resumed loops run only the remaining steps


# ---- signal-handler restoration contract (round-10 satellite) --------------
# TrainLoop.run promises hooks' process-wide handlers (PreemptionHook) are
# restored on exit. Pin it for all three exit shapes: normal completion,
# exception exit, and nested loops (an inner loop's hook must hand back the
# outer loop's handler, not the process original).


def _preemption_fixture(tmp_path, name):
    from distributed_tensorflow_guide_tpu.train.checkpoint import Checkpointer
    from distributed_tensorflow_guide_tpu.train.elastic import PreemptionHook

    ckpt = Checkpointer(tmp_path / name)
    return ckpt, PreemptionHook(ckpt)


def test_signal_handler_restored_on_normal_exit(tmp_path):
    import signal

    original = signal.getsignal(signal.SIGTERM)
    ckpt, hook = _preemption_fixture(tmp_path, "normal")
    TrainLoop(_toy_step, 0.0, _ones(),
              hooks=[StopAtStepHook(3), hook]).run()
    assert signal.getsignal(signal.SIGTERM) == original
    ckpt.close()


def test_signal_handler_restored_on_exception_exit(tmp_path):
    import signal

    import pytest

    original = signal.getsignal(signal.SIGTERM)
    ckpt, hook = _preemption_fixture(tmp_path, "crash")

    def boom(state, batch):
        if state >= 2.0:
            raise ValueError("mid-run crash")
        return _toy_step(state, batch)

    with pytest.raises(ValueError, match="mid-run crash"):
        TrainLoop(boom, 0.0, _ones(), hooks=[hook]).run()
    # the flag-only handler is gone even though end() never ran
    assert signal.getsignal(signal.SIGTERM) == original
    ckpt.close()


def test_signal_handler_restored_across_nested_loops(tmp_path):
    """An inner TrainLoop (e.g. a mid-training eval/fine-tune phase driven
    from a hook or from the step path) installs its own PreemptionHook:
    while it runs, ITS handler is live; when it exits, the OUTER loop's
    handler must be back (not the process original); when the outer loop
    exits, the process original is back."""
    import signal

    original = signal.getsignal(signal.SIGTERM)
    ckpt_o, outer_hook = _preemption_fixture(tmp_path, "outer")
    ckpt_i, inner_hook = _preemption_fixture(tmp_path, "inner")
    seen = {}

    def outer_step(state, batch):
        if state == 1.0 and "during_inner" not in seen:
            outer_handler = signal.getsignal(signal.SIGTERM)

            def inner_step(s, b):
                seen["during_inner"] = signal.getsignal(signal.SIGTERM)
                return _toy_step(s, b)

            TrainLoop(inner_step, 0.0, _ones(),
                      hooks=[StopAtStepHook(2), inner_hook]).run()
            seen["after_inner"] = signal.getsignal(signal.SIGTERM)
            # inner exit restored the OUTER hook's handler exactly
            assert seen["after_inner"] == outer_handler
        return _toy_step(state, batch)

    TrainLoop(outer_step, 0.0, _ones(),
              hooks=[StopAtStepHook(4), outer_hook]).run()
    # the inner loop really ran under its own handler, distinct from outer's
    assert seen["during_inner"] == inner_hook._on_signal
    assert seen["after_inner"] == outer_hook._on_signal
    assert signal.getsignal(signal.SIGTERM) == original
    ckpt_o.close()
    ckpt_i.close()
