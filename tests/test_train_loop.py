import json

import jax.numpy as jnp

from distributed_tensorflow_guide_tpu.train import (
    LoggingHook,
    MetricsJSONLHook,
    StepCounterHook,
    StopAtStepHook,
    TrainLoop,
)


def _toy_step(state, batch):
    return state + batch, {"loss": jnp.asarray(1.0 / (state + 1.0))}


def _ones():
    while True:
        yield 1.0


def test_stop_at_step():
    loop = TrainLoop(_toy_step, 0.0, _ones(), hooks=[StopAtStepHook(5)])
    final = loop.run()
    assert loop.step == 5
    assert final == 5.0


def test_data_exhaustion_stops_loop():
    loop = TrainLoop(_toy_step, 0.0, [1.0, 1.0, 1.0])
    final = loop.run()
    assert loop.step == 3 and final == 3.0


def test_metrics_jsonl(tmp_path):
    path = tmp_path / "metrics.jsonl"
    loop = TrainLoop(
        _toy_step,
        0.0,
        _ones(),
        hooks=[StopAtStepHook(4), MetricsJSONLHook(path, every_steps=2)],
    )
    loop.run()
    recs = [json.loads(l) for l in path.read_text().splitlines()]
    assert [r["step"] for r in recs] == [0, 2]
    assert abs(recs[1]["loss"] - 1.0 / 3.0) < 1e-6


def test_step_counter_measures():
    h = StepCounterHook(every_steps=2, batch_size=8, n_chips=2)
    loop = TrainLoop(_toy_step, 0.0, _ones(), hooks=[StopAtStepHook(7), h])
    loop.run()
    assert h.last_steps_per_sec is not None and h.last_steps_per_sec > 0
    assert h.last_examples_per_sec_per_chip == h.last_steps_per_sec * 4


def test_logging_hook_runs(caplog):
    import logging

    with caplog.at_level(logging.INFO, logger="dtg.train"):
        TrainLoop(
            _toy_step, 0.0, _ones(), hooks=[StopAtStepHook(3), LoggingHook(1)]
        ).run()
    assert any("loss=" in r.message for r in caplog.records)


def test_start_step_resume_semantics():
    loop = TrainLoop(_toy_step, 0.0, _ones(), hooks=[StopAtStepHook(10)], start_step=7)
    loop.run()
    assert loop.step == 10  # resumed loops run only the remaining steps
