"""Watchdog: deadlines that convert stalls into prompt, diagnosable
failures (utils/watchdog.py) — plus the TrainLoop and prefetch wiring."""

import time

import pytest

from distributed_tensorflow_guide_tpu.utils.watchdog import (
    DataStallError,
    TripInfo,
    Watchdog,
    WatchdogTimeout,
)


def test_no_trip_within_deadline():
    with Watchdog(poll_s=0.005) as wd:
        wd.arm("quick work", 5.0)
        time.sleep(0.02)
        wd.disarm()
        wd.check()  # no raise
    assert wd.tripped is None


def test_trip_records_and_check_raises():
    trips = []
    with Watchdog(action=trips.append, poll_s=0.005) as wd:
        wd.arm("slow work", 0.03)
        time.sleep(0.15)
        with pytest.raises(WatchdogTimeout, match="slow work"):
            wd.check()
    assert len(trips) == 1 and isinstance(trips[0], TripInfo)
    assert trips[0].tag == "slow work" and trips[0].waited_s >= 0.03


def test_rearm_clears_previous_trip():
    with Watchdog(action=lambda info: None, poll_s=0.005) as wd:
        wd.arm("a", 0.02)
        time.sleep(0.1)
        assert wd.tripped is not None
        wd.arm("b", 5.0)  # a fresh guard must not inherit the stale trip
        wd.disarm()
        wd.check()


def test_diagnostics_dump_written(tmp_path):
    diag = tmp_path / "stacks.txt"
    with Watchdog(action=lambda info: None, diag_path=diag,
                  poll_s=0.005) as wd:
        wd.arm("stuck section", 0.02)
        time.sleep(0.1)
    text = diag.read_text()
    assert "stuck section" in text
    assert "Thread" in text or "File" in text  # faulthandler stack content


def test_interrupt_action_breaks_python_stall():
    """The default action interrupts the MAIN thread mid-Python-stall —
    the caller's except KeyboardInterrupt + check() converts it."""
    with Watchdog(poll_s=0.005) as wd:
        wd.arm("stall", 0.05)
        with pytest.raises((KeyboardInterrupt, WatchdogTimeout)):
            try:
                for _ in range(1000):
                    time.sleep(0.01)
            except KeyboardInterrupt:
                wd.check()  # converts to the clean error
                raise  # pragma: no cover - check always raises here
        wd.disarm()


def test_invalid_action_and_deadline_rejected():
    with pytest.raises(ValueError, match="action"):
        Watchdog(action="detonate")
    with Watchdog(poll_s=0.005) as wd:
        with pytest.raises(ValueError, match="deadline"):
            wd.arm("x", 0.0)


# ---- TrainLoop wiring -------------------------------------------------------


def _toy_step(state, batch):
    return state + batch, {"loss": state}


def test_train_loop_data_deadline_converts_stall():
    """A stalled data iterator becomes a WatchdogTimeout — a RECOVERABLE
    RuntimeError run_with_recovery treats like any crash — instead of
    hanging to the supervisor's full wall-clock timeout."""
    from distributed_tensorflow_guide_tpu.train.loop import TrainLoop

    def stalling_data():
        yield 1.0
        while True:  # Python-level stall, the watchdog's documented prey
            time.sleep(0.01)

    loop = TrainLoop(_toy_step, 0.0, stalling_data(), data_deadline_s=0.2)
    with pytest.raises(WatchdogTimeout, match="data iterator"):
        loop.run()
    assert loop.step == 1  # the good batch ran; the stall was converted


def test_train_loop_step_deadline_converts_slow_hook():
    """The step guard covers dispatch + hook fan-out (where a wedged device
    surfaces as a blocking metric read)."""
    from distributed_tensorflow_guide_tpu.train.hooks import BaseHook
    from distributed_tensorflow_guide_tpu.train.loop import TrainLoop

    class StuckHook(BaseHook):
        def after_step(self, step, metrics):
            if step == 2:
                while True:
                    time.sleep(0.01)

    loop = TrainLoop(_toy_step, 0.0, iter([1.0] * 100), hooks=[StuckHook()],
                     step_deadline_s=0.2)
    with pytest.raises(WatchdogTimeout, match="train step"):
        loop.run()


def test_train_loop_without_deadlines_has_no_watchdog():
    from distributed_tensorflow_guide_tpu.train.loop import TrainLoop

    loop = TrainLoop(_toy_step, 0.0, iter([1.0] * 3))
    assert loop.run() == 3.0  # no watchdog machinery engaged at all


def test_train_loop_deadline_not_tripped_by_fast_steps():
    from distributed_tensorflow_guide_tpu.train.loop import TrainLoop

    loop = TrainLoop(_toy_step, 0.0, iter([1.0] * 20),
                     step_deadline_s=5.0, data_deadline_s=5.0)
    assert loop.run() == 20.0 and loop.step == 20


# ---- prefetch wiring --------------------------------------------------------


def test_prefetch_max_host_wait_raises_data_stall():
    from distributed_tensorflow_guide_tpu.data.prefetch import (
        DevicePrefetchIterator,
    )

    def slow_source():
        yield {"x": 1.0}
        time.sleep(0.3)
        yield {"x": 2.0}

    it = DevicePrefetchIterator(slow_source(), depth=1, put_fn=lambda b: b,
                                max_host_wait_s=0.05)
    # the eager refill (the line that buys the overlap) fetches batch 2
    # inside the FIRST next(), so the stall surfaces there — fail-fast
    # means the error preempts the buffered batch
    with pytest.raises(DataStallError, match="max_host_wait_s"):
        next(it)


def test_prefetch_stats_track_max_single_wait():
    from distributed_tensorflow_guide_tpu.data.prefetch import (
        DevicePrefetchIterator,
    )

    def source():
        yield {"x": 1.0}
        time.sleep(0.1)
        yield {"x": 2.0}

    it = DevicePrefetchIterator(source(), depth=1, put_fn=lambda b: b)
    list(it)
    assert it.stats.max_host_wait_s >= 0.1
    assert "prefetch_max_host_wait_s" in it.stats.as_dict()


def test_prefetch_rejects_bad_deadline():
    from distributed_tensorflow_guide_tpu.data.prefetch import (
        DevicePrefetchIterator,
    )

    with pytest.raises(ValueError, match="max_host_wait_s"):
        DevicePrefetchIterator(iter([]), max_host_wait_s=0.0)


# ---- coordinator-init retry (core/dist.py) ---------------------------------


def test_retry_with_backoff_retries_then_succeeds():
    from distributed_tensorflow_guide_tpu.core.dist import retry_with_backoff

    calls, delays = [], []

    def flaky():
        calls.append(1)
        if len(calls) < 3:
            raise RuntimeError("coordinator not up yet")
        return "connected"

    out = retry_with_backoff(flaky, attempts=4, base_delay_s=1.0,
                             sleep=delays.append, what="handshake")
    assert out == "connected" and len(calls) == 3
    assert delays == [1.0, 2.0]  # exponential, deterministic


def test_retry_with_backoff_exhausts_and_reraises():
    from distributed_tensorflow_guide_tpu.core.dist import retry_with_backoff

    delays = []
    with pytest.raises(RuntimeError, match="still down"):
        retry_with_backoff(
            lambda: (_ for _ in ()).throw(RuntimeError("still down")),
            attempts=3, base_delay_s=0.5, max_delay_s=0.75,
            sleep=delays.append,
        )
    assert delays == [0.5, 0.75]  # capped at max_delay_s


def test_retry_with_backoff_does_not_catch_foreign_errors():
    from distributed_tensorflow_guide_tpu.core.dist import retry_with_backoff

    with pytest.raises(KeyError):
        retry_with_backoff(lambda: {}["missing"], attempts=5,
                           sleep=lambda s: None)
