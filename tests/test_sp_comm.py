"""Communication accounting for the SP layouts — traced-vs-analytic parity.

Pins the identity benchmarks/bench_sp_comm.py relies on: tracing the real
ring / Ulysses shard_map programs under ``collectives.trace_comm`` yields
exactly the call sites and per-device shard bytes the designs predict
(SURVEY.md §5 long-context row; ring = Liu et al. blockwise + KV rotation,
Ulysses = Jacobs et al. all_to_all head-resharding)."""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

import distributed_tensorflow_guide_tpu.collectives as cc
from distributed_tensorflow_guide_tpu.core.mesh import MeshSpec, build_mesh
from distributed_tensorflow_guide_tpu.parallel.sequence import (
    ring_attention,
    ulysses_attention,
)

B, S, H, D = 2, 512, 8, 32


@pytest.fixture()
def ctx_mesh():
    return build_mesh(MeshSpec(data=-1, context=4))


def _lower(mesh, fn):
    # global (B, S, H, D); shard_map hands each device (B, S/4, H, D)
    x = jnp.zeros((B, S, H, D), jnp.float32)
    sm = jax.shard_map(
        fn, mesh=mesh,
        in_specs=(P(None, "context"),) * 3,
        out_specs=P(None, "context"),
        check_vma=False,
    )
    with cc.trace_comm() as rec:
        jax.jit(sm).lower(x, x, x)
    local_bytes = int(np.prod((B, S // 4, H, D))) * 4
    return rec, local_bytes


def test_ring_comm_sites(ctx_mesh):
    rec, t = _lower(
        ctx_mesh, functools.partial(ring_attention, causal=True, impl="xla")
    )
    # one K + one V ppermute site inside the rotation scan, each a full
    # local shard; executed n times per step (the scan body traces once)
    assert rec.calls["ppermute[context]"] == 2
    assert rec.bytes["ppermute[context]"] == 2 * t
    assert rec.calls.get("all_to_all[context]", 0) == 0


def test_ulysses_comm_sites(ctx_mesh):
    rec, t = _lower(
        ctx_mesh,
        functools.partial(ulysses_attention, causal=True, impl="dense"),
    )
    # q/k/v reshard seq->heads plus the output's heads->seq return trip
    assert rec.calls["all_to_all[context]"] == 4
    assert rec.bytes["all_to_all[context]"] == 4 * t
    assert rec.calls.get("ppermute[context]", 0) == 0


def test_ring_pallas_fwd_bwd_comm_sites(ctx_mesh):
    """The backward accounting the round-3 table ignored, pinned: the
    Pallas ring's hand-written backward rotates FOUR tensors per hop
    (k, v, dk-partial, dv-partial) through the wrapper layer, so
    grad-tracing records 2 forward-rule + 4 backward sites. Byte check is
    double duty: at D=32 on the 128-lane kernel, each site must move the
    UNPADDED shard (t bytes, not 4t) — rotating kernel-padded tensors
    would quadruple the wire bytes at this head dim (the pad is applied
    locally per visit instead; see sequence.py ``_pad_lane``)."""
    x = jnp.zeros((B, S, H, D), jnp.float32)
    sm = jax.shard_map(
        functools.partial(ring_attention, causal=True, impl="pallas"),
        mesh=ctx_mesh,
        in_specs=(P(None, "context"),) * 3,
        out_specs=P(None, "context"),
        check_vma=False,
    )

    def loss(q, k, v):
        return jnp.sum(sm(q, k, v).astype(jnp.float32))

    with cc.trace_comm() as rec:
        jax.jit(jax.grad(loss, argnums=(0, 1, 2))).lower(x, x, x)
    t = int(np.prod((B, S // 4, H, D))) * 4
    assert rec.calls["ppermute[context]"] == 6, dict(rec.calls)
    assert rec.bytes["ppermute[context]"] == 6 * t, (
        rec.bytes["ppermute[context]"], t)
