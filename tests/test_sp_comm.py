"""Communication accounting for the SP layouts — traced-vs-analytic parity.

Pins the identity benchmarks/bench_sp_comm.py relies on: tracing the real
ring / Ulysses shard_map programs under ``collectives.trace_comm`` yields
exactly the call sites and per-device shard bytes the designs predict
(SURVEY.md §5 long-context row; ring = Liu et al. blockwise + KV rotation,
Ulysses = Jacobs et al. all_to_all head-resharding)."""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

import distributed_tensorflow_guide_tpu.collectives as cc
from distributed_tensorflow_guide_tpu.core.compat import shard_map
from distributed_tensorflow_guide_tpu.core.mesh import MeshSpec, build_mesh
from distributed_tensorflow_guide_tpu.parallel.sequence import (
    ring_attention,
    ulysses_attention,
)

B, S, H, D = 2, 512, 8, 32


@pytest.fixture()
def ctx_mesh():
    return build_mesh(MeshSpec(data=-1, context=4))


def _lower(mesh, fn):
    # global (B, S, H, D); shard_map hands each device (B, S/4, H, D)
    x = jnp.zeros((B, S, H, D), jnp.float32)
    sm = shard_map(
        fn, mesh=mesh,
        in_specs=(P(None, "context"),) * 3,
        out_specs=P(None, "context"),
        check_vma=False,
    )
    with cc.trace_comm() as rec:
        jax.jit(sm).lower(x, x, x)
    local_bytes = int(np.prod((B, S // 4, H, D))) * 4
    return rec, local_bytes


def test_ring_comm_sites(ctx_mesh):
    rec, t = _lower(
        ctx_mesh, functools.partial(ring_attention, causal=True, impl="xla")
    )
    # one K + one V ppermute site inside the rotation scan, each a full
    # local shard; executed n times per step (the scan body traces once)
    assert rec.calls["ppermute[context]"] == 2
    assert rec.bytes["ppermute[context]"] == 2 * t
    assert rec.calls.get("all_to_all[context]", 0) == 0


def test_ulysses_comm_sites(ctx_mesh):
    rec, t = _lower(
        ctx_mesh,
        functools.partial(ulysses_attention, causal=True, impl="dense"),
    )
    # q/k/v reshard seq->heads plus the output's heads->seq return trip
    assert rec.calls["all_to_all[context]"] == 4
    assert rec.bytes["all_to_all[context]"] == 4 * t
    assert rec.calls.get("ppermute[context]", 0) == 0


def test_ring_pallas_fwd_bwd_comm_sites(ctx_mesh):
    """Backward comm accounting, pinned: the Pallas ring's hand-written
    Q-SIDE backward rotates THREE head_dim-sized tensors per hop (q, the
    output cotangent, the travelling dq partial) plus two lane-thin
    softmax stats (lse's first lane, delta) — 5 backward sites on top of
    the 2 forward-rule ones. Byte check is double duty: at D=32 on the
    128-lane kernel every head_dim site must move the UNPADDED shard
    (t bytes, not 4t) and the two stat rows t/D each — rotating padded
    tensors or the full lane-broadcast lse would blow this sum up (the
    pad and broadcast are applied locally per visit instead)."""
    x = jnp.zeros((B, S, H, D), jnp.float32)
    sm = shard_map(
        functools.partial(ring_attention, causal=True, impl="pallas"),
        mesh=ctx_mesh,
        in_specs=(P(None, "context"),) * 3,
        out_specs=P(None, "context"),
        check_vma=False,
    )

    def loss(q, k, v):
        return jnp.sum(sm(q, k, v).astype(jnp.float32))

    with cc.trace_comm() as rec:
        jax.jit(jax.grad(loss, argnums=(0, 1, 2))).lower(x, x, x)
    t = int(np.prod((B, S // 4, H, D))) * 4
    thin = t // D  # one f32 per (batch, head, position): lse1 or delta
    assert rec.calls["ppermute[context]"] == 7, dict(rec.calls)
    assert rec.bytes["ppermute[context]"] == 5 * t + 2 * thin, (
        rec.bytes["ppermute[context]"], t, thin)


def test_ring_pallas_optin_is_never_silent(ctx_mesh, caplog):
    """Explicitly opting into impl='pallas' must warn once per shape,
    citing the last measured pallas/xla ratio (round-5 battery), through
    the package's single degradation registry (fallback_stats) — the
    opt-in path is allowed to be slow, never silently slow."""
    import logging

    from distributed_tensorflow_guide_tpu.ops.flash_attention import (
        fallback_stats,
    )
    from distributed_tensorflow_guide_tpu.parallel.sequence import (
        RING_PALLAS_LAST_MEASURED,
    )

    d_odd = 48  # unique shape so the once-per-shape warning fires HERE
    x = jnp.zeros((B, S, H, d_odd), jnp.float32)
    sm = shard_map(
        functools.partial(ring_attention, causal=True, impl="pallas"),
        mesh=ctx_mesh,
        in_specs=(P(None, "context"),) * 3,
        out_specs=P(None, "context"),
        check_vma=False,
    )
    key = ("ring_attention_pallas_optin", S // 4, d_odd, 0, 0)
    before = fallback_stats().get(key, 0)
    with caplog.at_level(logging.WARNING, logger="dtg.ops.flash"):
        # the warning fires at TRACE time — eval_shape is enough (no
        # Mosaic lowering; keeps the tier-1 suite cheap)
        jax.eval_shape(sm, x, x, x)
    assert fallback_stats().get(key, 0) == before + 1
    if before == 0:
        msgs = [r.message for r in caplog.records]
        assert any("0.157" in m and "impl='pallas'" in m for m in msgs), msgs
    # the measured-ratio constant the warning cites stays a real dict
    assert set(RING_PALLAS_LAST_MEASURED) == {1024, 2048, 4096}


def test_ring_auto_selects_measured_winner(ctx_mesh):
    """impl='auto' must select the XLA blockwise path — the on-chip winner
    at every measured length (round-5 battery: Pallas at 0.157–0.487x of
    XLA at seq 1k/2k/4k) — even for lane-aligned shapes the kernel could
    run. The two paths share the forward trace signature (2 ppermute
    sites), so the pin is the GRAD trace: the Pallas path's hand-written
    backward issues 5 more wrapper-visible ppermute sites, while the XLA
    path's backward comes from autodiff transposes that bypass the
    wrappers — auto must show the XLA signature."""

    def grad_sites(impl, s):
        x = jnp.zeros((B, s, H, D), jnp.float32)
        sm = shard_map(
            functools.partial(ring_attention, causal=True, impl=impl),
            mesh=ctx_mesh,
            in_specs=(P(None, "context"),) * 3,
            out_specs=P(None, "context"),
            check_vma=False,
        )

        def loss(q, k, v):
            return jnp.sum(sm(q, k, v).astype(jnp.float32))

        with cc.trace_comm() as rec:
            jax.jit(jax.grad(loss, argnums=(0, 1, 2))).lower(x, x, x)
        return rec.calls["ppermute[context]"]

    aligned = 4 * 128   # the kernel COULD run here; auto must still say xla
    assert grad_sites("pallas", aligned) == 7
    assert grad_sites("xla", aligned) == 2
    assert grad_sites("auto", aligned) == 2
    # non-aligned shapes: auto runs xla too (and pallas refuses, pinned in
    # test_attention.py) — no silent path switch in either direction
    assert grad_sites("auto", 4 * 96) == 2
