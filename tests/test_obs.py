"""Observability plane (obs/): the acceptance pin is INERTNESS —
recorder ON vs OFF must be bitwise-invisible to every compiled path
(engine completions across the decode levers with zero new compiles, a
50-step train loop's final state), while the recorder itself must be
exactly reproducible under seeded chaos, dump a usable black box on
watchdog/give-up trips, export schema-valid Chrome traces, and join
static cost vectors against measured time with pinned closed forms."""

import dataclasses
import json
import math
import time
from types import SimpleNamespace

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_tensorflow_guide_tpu.models.transformer import (
    Transformer,
    TransformerConfig,
)
from distributed_tensorflow_guide_tpu.obs import events as obs_events
from distributed_tensorflow_guide_tpu.obs import metrics as obs_metrics
from distributed_tensorflow_guide_tpu.obs import recon as obs_recon
from distributed_tensorflow_guide_tpu.obs import tracing as obs_trace
from distributed_tensorflow_guide_tpu.serve import Request, ServeEngine
from distributed_tensorflow_guide_tpu.serve import engine as serve_engine
from distributed_tensorflow_guide_tpu.testing.chaos import FaultSchedule
from distributed_tensorflow_guide_tpu.train.hooks import (
    MetricsHook,
    StopAtStepHook,
)
from distributed_tensorflow_guide_tpu.train.loop import TrainLoop

# same geometry as tests/test_serving.py: the engine step-fn memo is
# keyed by (cfg, geometry, sampling), so these runs share its compiles —
# recorder tests must never pay (or cause) a new compile.
CFG = TransformerConfig(vocab_size=64, num_layers=2, num_heads=2,
                        d_model=16, d_ff=32, max_len=64, causal=True,
                        dtype=jnp.float32)
PROMPTS = [np.array([3, 5, 7, 9, 11], np.int32),
           np.array([2, 4, 6, 8, 10, 12, 14, 16, 18], np.int32),
           np.array([1] * 17, np.int32)]
MAX_NEW = [8, 6, 10]


@pytest.fixture(scope="module")
def params():
    return Transformer(CFG).init(
        jax.random.PRNGKey(0), jnp.zeros((2, 8), jnp.int32))["params"]


def _engine(cfg, params, *, recorder=None, prompts=PROMPTS,
            max_new=MAX_NEW, **kw):
    kw.setdefault("slots", 2)
    kw.setdefault("num_blocks", 33)
    kw.setdefault("block_size", 8)
    kw.setdefault("prefill_chunk", 8)
    eng = ServeEngine(cfg, params, temperature=0.8, top_k=10,
                      recorder=recorder, **kw)
    for i, (p, mn) in enumerate(zip(prompts, max_new)):
        eng.submit(Request(rid=i, prompt=p, max_new_tokens=mn,
                           rng=jax.random.PRNGKey(100 + i)))
    return eng


def _drive(eng):
    """Step with a finite virtual clock (like bench_serving's driver) so
    every event carries a real semantic timestamp."""
    now = 0.0
    while (eng.sched.has_queued or eng.sched.has_resident
           or eng._pressure_holds):
        eng.step(now)
        now += 0.01


# ---- ring semantics ---------------------------------------------------------


def test_ring_drops_oldest_and_counts():
    rec = obs_events.FlightRecorder(capacity=4)
    for i in range(10):
        rec.emit("k", payload={"i": i})
    assert len(rec) == 4 and rec.total == 10 and rec.dropped == 6
    assert [e.payload["i"] for e in rec.events()] == [6, 7, 8, 9]
    assert [e.seq for e in rec.events()] == [6, 7, 8, 9]
    rec.clear()
    assert len(rec) == 0 and rec.total == 10  # history count survives
    with pytest.raises(ValueError, match="capacity"):
        obs_events.FlightRecorder(capacity=0)


def test_dump_roundtrip_signature_and_volatile_keys(tmp_path):
    def mk(dur):
        rec = obs_events.FlightRecorder(clock=lambda: 2.5)
        rec.emit("req.admit", cat="serve", actor="sched",
                 payload={"rid": 1, "queue_wait_s": dur})
        rec.emit("decode.launch", cat="serve", actor="engine",
                 payload={"slots": [0], "rids": [1], "dur_s": dur})
        return rec

    a, b = mk(0.111), mk(0.999)
    # wall-measured durations are VOLATILE: they differ run to run and
    # must not break the reproducibility signature
    assert obs_events.signature(a.events()) == \
        obs_events.signature(b.events())
    sig_t = obs_events.signature(a.events(), include_t=True)
    assert all(row[3] == 2.5 for row in sig_t)  # injected clock stamped

    path = a.dump(str(tmp_path / "d.json"))
    data = json.loads(open(path).read())
    assert data["schema"] == obs_events.SCHEMA
    assert data["total"] == 2 and data["dropped"] == 0
    back = obs_trace.events_from_dump(path)
    assert obs_events.signature(back) == obs_events.signature(a.events())
    # non-finite floats become null in strict JSON
    a.emit("x", payload={"v": float("inf")})
    data = json.loads(open(a.dump(str(tmp_path / "e.json"))).read())
    assert data["events"][-1]["payload"]["v"] is None


def test_crash_dump_black_box(tmp_path):
    bb = tmp_path / "bb.json"
    rec = obs_events.FlightRecorder(crash_dump_path=str(bb))
    rec.emit("before", payload={})
    out = rec.crash_dump("watchdog.trip", cat="watchdog",
                         payload={"tag": "step"})
    assert out == str(bb)
    dumped = json.loads(bb.read_text())
    assert [e["kind"] for e in dumped["events"]] == \
        ["before", "watchdog.trip"]
    # no path configured: the event still lands, nothing is written
    rec2 = obs_events.FlightRecorder()
    assert rec2.crash_dump("x") is None and rec2.total == 1


def test_null_recorder_and_install():
    null = obs_events.NULL_RECORDER
    assert not null.enabled and null.emit("k") is None
    assert null.events() == [] and len(null) == 0
    assert null.crash_dump("k") is None
    rec = obs_events.FlightRecorder()
    prev = obs_events.install(rec)
    try:
        assert obs_events.current() is rec
    finally:
        obs_events.install(prev)
    assert obs_events.current() is prev


# ---- metrics registry -------------------------------------------------------


def test_registry_counters_gauges_histograms():
    reg = obs_metrics.Registry()
    reg.counter("dtg_c", "help").inc(3)
    reg.counter("dtg_c").inc()  # get-or-create returns the same metric
    with pytest.raises(ValueError, match="decrease"):
        reg.counter("dtg_c").inc(-1)
    reg.gauge("dtg_g", labels={"tenant": "0"}).set(2.5)
    h = reg.histogram("dtg_h", buckets=(0.1, 1.0))
    h.observe(0.05)
    h.observe(0.5)
    h.observe(5.0)
    snap = reg.snapshot()
    assert snap["dtg_c"] == 4.0
    assert snap['dtg_g{tenant="0"}'] == 2.5
    assert snap["dtg_h"]["count"] == 3 and snap["dtg_h"]["sum"] == 5.55
    assert snap["dtg_h"]["buckets"] == {0.1: 1, 1.0: 2}
    with pytest.raises(TypeError, match="registered as counter"):
        reg.gauge("dtg_c")
    text = reg.to_prometheus()
    assert "# TYPE dtg_c counter" in text
    assert 'dtg_g{tenant="0"} 2.5' in text
    assert 'dtg_h_bucket{le="+Inf"} 3' in text
    assert "dtg_h_sum 5.55" in text


def test_absorbers_map_existing_stats():
    reg = obs_metrics.Registry()
    obs_metrics.absorb_dispatch(reg, SimpleNamespace(
        dispatches=4, steps=2, host_gap_s=0.2, dispatch_s=0.05))
    obs_metrics.absorb_prefetch(reg, SimpleNamespace(
        batches=3, host_wait_s=0.1, max_host_wait_s=0.08, put_s=0.01,
        peak_ahead=2))
    snap = reg.snapshot()
    assert snap["dtg_train_dispatches_total"] == 4
    assert snap["dtg_train_host_gap_ms_per_dispatch"] == \
        pytest.approx(50.0)
    assert snap["dtg_data_prefetch_batches_total"] == 3
    assert snap["dtg_data_prefetch_peak_ahead"] == 2


def test_pool_and_prefix_stats_shapes():
    from distributed_tensorflow_guide_tpu.serve import BlockPool
    from distributed_tensorflow_guide_tpu.serve.prefix_index import (
        PrefixIndex,
    )

    pool = BlockPool(num_blocks=5, block_size=8)
    blocks = pool.alloc(1, 1)
    pool.share(2, blocks)  # refcount 2 -> one live block, shared
    s = pool.stats()
    assert s == {"capacity": 4, "free": 3, "live": 1, "shared": 1,
                 "holds": 2}
    pool.free(1, blocks)
    pool.free(2, blocks)
    pool.check_leaks()
    assert pool.stats()["free"] == 4 and pool.stats()["shared"] == 0

    idx = PrefixIndex(block_size=4)
    assert idx.stats() == {"nodes": 0, "leaves": 0, "max_depth": 0,
                           "adapters": 0, "spilled": 0}
    reg = obs_metrics.Registry()
    obs_metrics.absorb_pool(reg, s)
    obs_metrics.absorb_prefix(reg, idx.stats())
    snap = reg.snapshot()
    assert snap["dtg_serve_pool_live"] == 1
    assert snap["dtg_serve_prefix_nodes"] == 0
    assert snap["dtg_serve_prefix_spilled"] == 0


# ---- chrome trace exporter --------------------------------------------------


def test_chrome_exporter_schema():
    rec = obs_events.FlightRecorder()
    rec.emit("span.begin", cat="train",
             payload={"name": "s", "track": "loop", "step": 0}, t=1.0)
    rec.emit("span.end", cat="train",
             payload={"name": "s", "track": "loop"}, t=2.0)
    rec.emit("prefill.launch", cat="serve",
             payload={"slot": 0, "rid": 1, "chunk": 8, "dur_s": 0.5},
             t=3.0)
    rec.emit("decode.launch", cat="serve",
             payload={"slots": [0, 1], "rids": [1, 2], "tick": 1,
                      "dur_s": 0.25}, t=4.0)
    rec.emit("req.admit", cat="serve",
             payload={"rid": 3, "slot": 1, "queue_wait_s": 0.5}, t=5.0)
    rec.emit("req.done", cat="serve", payload={"rid": 1, "tick": 2},
             t=6.0)
    rec.emit("req.admit", cat="serve", payload={"rid": 9},
             t=float("inf"))  # engine.run() drains at now=inf: skipped

    trace = obs_trace.to_chrome_trace(rec.events())
    json.dumps(trace)  # strict-JSON serializable
    evs = trace["traceEvents"]
    meta = [e for e in evs if e["ph"] == "M"]
    real = [e for e in evs if e["ph"] != "M"]
    # the non-finite-clock event is dropped, everything else lands
    assert not any(e.get("args", {}).get("rid") == 9 for e in real)
    # B/E pair up per (pid, tid, name)
    b = [(e["pid"], e["tid"], e["name"]) for e in real if e["ph"] == "B"]
    e_ = [(e["pid"], e["tid"], e["name"]) for e in real
          if e["ph"] == "E"]
    assert b and sorted(b) == sorted(e_)
    # decode.launch fans out to one X per (slot, rid)
    xs = [e for e in real if e["ph"] == "X"]
    assert {e["name"] for e in xs} >= {"decode rid1", "decode rid2",
                                       "prefill rid1"}
    # the queue-wait bar is backdated by exactly the admit's wait
    bar = next(e for e in xs if e["name"] == "rid3 queued")
    assert bar["ts"] == pytest.approx(5.0e6 - 0.5e6)
    assert bar["dur"] == pytest.approx(0.5e6)
    # every (pid, tid) in use carries exactly one thread_name M record
    used = {(e["pid"], e["tid"]) for e in real}
    named = [(e["pid"], e["tid"]) for e in meta
             if e["name"] == "thread_name"]
    assert len(named) == len(set(named)) and used <= set(named)
    assert {e["pid"] for e in real} == \
        {e["pid"] for e in meta if e["name"] == "process_name"}
    # instants carry scope + ts
    inst = [e for e in real if e["ph"] == "i"]
    assert inst and all(e["s"] == "t" and math.isfinite(e["ts"])
                        for e in inst)


# ---- inertness: recorder on/off is bitwise-invisible ------------------------


def test_engine_bitwise_parity_and_zero_new_compiles(params):
    eng_off = _engine(CFG, params)
    eng_off.run()
    compiled = len(serve_engine._STEP_FNS)

    rec = obs_events.FlightRecorder()
    eng_on = _engine(CFG, params, recorder=rec)
    eng_on.run()
    assert eng_on.completions() == eng_off.completions()
    # the recorder caused no new program: same memoized geometry
    assert len(serve_engine._STEP_FNS) == compiled
    kinds = {e.kind for e in rec.events()}
    assert {"req.submit", "req.admit", "prefill.launch", "decode.launch",
            "req.first_token", "req.done"} <= kinds
    done = [e.payload["rid"] for e in rec.events()
            if e.kind == "req.done"]
    assert sorted(done) == [0, 1, 2]
    # determinism: an identical run produces the identical sequence
    rec2 = obs_events.FlightRecorder()
    eng2 = _engine(CFG, params, recorder=rec2)
    eng2.run()
    assert obs_events.signature(rec2.events()) == \
        obs_events.signature(rec.events())


@pytest.mark.parametrize("kv,impl", [("int8", "dense"), (None, "pallas"),
                                     ("int8", "pallas")])
def test_engine_parity_across_decode_levers(params, kv, impl):
    """The PR-10 lever geometries (identical to test_serving's, so the
    step-fn memo is shared): recording must be invisible under each."""
    cfg = dataclasses.replace(CFG, kv_dtype=kv, decode_impl=impl)
    kw = dict(prompts=PROMPTS[:2], max_new=MAX_NEW[:2], num_blocks=17)
    eng_off = _engine(cfg, params, **kw)
    eng_off.run()
    rec = obs_events.FlightRecorder()
    eng_on = _engine(cfg, params, recorder=rec, **kw)
    eng_on.run()
    assert eng_on.completions() == eng_off.completions(), \
        f"kv={kv} impl={impl}"
    assert {e.kind for e in rec.events()} >= {"req.done"}


def test_train_loop_bitwise_parity_and_spans():
    @jax.jit
    def step(state, batch):
        new = state - 0.01 * (2 * state + batch)
        return new, {"loss": jnp.sum(state ** 2)}

    def data():
        return (jnp.full((4,), float(s)) for s in range(10_000))

    hooks = lambda: [StopAtStepHook(50)]  # noqa: E731
    off = TrainLoop(step, jnp.ones((4,)), data(), hooks=hooks()).run()
    rec = obs_events.FlightRecorder(capacity=1 << 12)
    on = TrainLoop(step, jnp.ones((4,)), data(), hooks=hooks(),
                   recorder=rec).run()
    np.testing.assert_array_equal(np.asarray(off), np.asarray(on))
    kinds = [e.kind for e in rec.events()]
    assert kinds.count("span.begin") == kinds.count("span.end") == 100
    names = [e.payload["name"] for e in rec.events()
             if e.kind == "span.begin"]
    assert names.count("data_wait") == names.count("dispatch") == 50
    steps = [e.payload["step"] for e in rec.events()
             if e.kind == "span.begin" and
             e.payload["name"] == "dispatch"]
    assert steps == list(range(50))


def test_metrics_hook_and_tb_roundtrip(tmp_path):
    from distributed_tensorflow_guide_tpu.utils.tb_writer import (
        SummaryWriter,
        read_scalars,
    )

    def step(state, batch):
        return state + batch, {"loss": jnp.asarray(state)}

    reg = obs_metrics.Registry()
    with SummaryWriter(tmp_path) as w:
        hook = MetricsHook(reg, every_steps=5, writer=w)
        TrainLoop(step, 0.0, (1.0 for _ in range(10_000)),
                  hooks=[StopAtStepHook(20), hook]).run()
    snap = reg.snapshot()
    assert snap["dtg_train_steps_total"] == 20
    assert snap["dtg_train_metric_loss"] == 19.0
    assert snap["dtg_train_dispatches_total"] == 20
    (event_file,) = tmp_path.glob("events.out.tfevents.*")
    rows = read_scalars(event_file)
    assert rows and rows[-1][1]["dtg_train_steps_total"] == 20.0
    assert any("dtg_train_metric_loss" in scalars
               for _, scalars in rows)


# ---- black boxes: watchdog trip + seeded chaos storm ------------------------


def test_watchdog_trip_dumps_flight_recorder(tmp_path):
    from distributed_tensorflow_guide_tpu.utils.watchdog import Watchdog

    diag = tmp_path / "stacks.txt"
    rec = obs_events.FlightRecorder()
    rec.emit("step.before", payload={"step": 7})
    with Watchdog(action=lambda info: None, diag_path=diag,
                  poll_s=0.005, recorder=rec) as wd:
        wd.arm("stuck section", 0.02)
        deadline = time.time() + 5
        while wd.tripped is None and time.time() < deadline:
            time.sleep(0.01)
    bb = tmp_path / "stacks.txt.flightrec.json"
    assert bb.exists()
    dumped = json.loads(bb.read_text())
    trip = dumped["events"][-1]
    assert trip["kind"] == "watchdog.trip"
    assert trip["payload"]["tag"] == "stuck section"
    assert trip["payload"]["deadline_s"] == 0.02
    assert trip["payload"]["waited_s"] >= 0.02
    # the context that led up to the trip is in the same tail
    assert dumped["events"][0]["kind"] == "step.before"


def test_seeded_chaos_storm_exactly_reproducible(params):
    kinds = ("serve_step_exception", "client_abandon", "pool_pressure")

    def run_once():
        sched = FaultSchedule.random_serve(
            11, max_position=12, kinds=kinds, n_faults=3)
        rec = obs_events.FlightRecorder()
        eng = _engine(CFG, params, recorder=rec, chaos=sched,
                      retry_base_delay_s=0.001)
        eng.run()
        return sched, rec, eng.completions()

    s1, r1, c1 = run_once()
    s2, r2, c2 = run_once()
    assert c1 == c2  # chaos absorbed identically
    assert obs_events.signature(r1.events()) == \
        obs_events.signature(r2.events())
    recorded = {(e.payload["kind"], e.payload["position"])
                for e in r1.events() if e.kind == "chaos.fault"}
    assert recorded == {(f.kind, f.position) for f in s1.fired}
    assert len(s1.fired) == len(s2.fired)


def test_ttft_breakdown_from_driven_engine(params):
    rec = obs_events.FlightRecorder()
    eng = _engine(CFG, params, recorder=rec)
    _drive(eng)
    trace = obs_trace.to_chrome_trace(rec.events())
    xs = [e for e in trace["traceEvents"] if e["ph"] == "X"]
    assert len(xs) > 0  # finite virtual clock -> real complete spans
    bk = obs_trace.ttft_breakdown(rec.events())
    assert sorted(bk) == [0, 1, 2]
    for rid, parts in bk.items():
        assert set(parts) == {"queue_wait_s", "prefill_s",
                              "first_decode_s"}
        assert parts["prefill_s"] > 0
        assert all(v >= 0 for v in parts.values())
    # the absorber must accept a REAL health() dict, not a hand-built one
    reg = obs_metrics.Registry()
    obs_metrics.absorb_engine(reg, eng.health())
    snap = reg.snapshot()
    assert snap["dtg_serve_completed_total"] == 3
    assert snap["dtg_serve_ticks_total"] > 0
    assert snap["dtg_serve_resident"] == 0


def test_spill_tier_absorbers_from_driven_engine(params):
    """The host-tier gauges flow from REAL shapes — a driven hierarchy-on
    engine's health() and its BlockStore's stats(), not hand-built dicts
    — so the absorbers break loudly if either producer drifts."""
    eng = _engine(CFG, params, host_blocks=8, prefix_cache=True)
    _drive(eng)
    sd = eng.sched
    freed = sd.prefix.demote_many(sd.pool, sd._cache_demote_batch)
    assert freed  # the driven prompts cached demotable full blocks
    reg = obs_metrics.Registry()
    obs_metrics.absorb_engine(reg, eng.health())
    obs_metrics.absorb_spill_store(reg, eng.store.stats())
    obs_metrics.absorb_prefix(reg, sd.prefix.stats())
    snap = reg.snapshot()
    assert snap["dtg_serve_spill_host_blocks"] == len(freed)
    assert snap["dtg_serve_spill_out_blocks_total"] == len(freed)
    assert snap["dtg_serve_spill_d2h_bytes_total"] > 0
    assert snap["dtg_serve_spill_host_bytes"] == eng.store.bytes_stored()
    assert snap["dtg_serve_spill_store_live"] == len(freed)
    assert snap["dtg_serve_spill_store_holds"] == len(freed)
    assert snap["dtg_serve_prefix_spilled"] == len(freed)
    eng.close()
    sd.check_leaks()


# ---- checkpoint / elastic events --------------------------------------------


def test_checkpointer_save_restore_events(tmp_path):
    from distributed_tensorflow_guide_tpu.train.checkpoint import (
        Checkpointer,
    )

    state = {"w": jnp.arange(4, dtype=jnp.float32)}
    rec = obs_events.FlightRecorder()
    ckpt = Checkpointer(tmp_path / "ckpt", recorder=rec)
    try:
        ckpt.save(3, state, force=True)
        ckpt.wait()
        restored = ckpt.restore_latest_valid(state)
        assert restored is not None and restored[1] == 3
    finally:
        ckpt.close()
    kinds = [e.kind for e in rec.events()]
    assert kinds == ["ckpt.save", "ckpt.restore"]
    save = rec.events()[0].payload
    assert save == {"step": 3, "async": False, "force": True}
    assert rec.events()[1].payload == {"step": 3, "skipped": []}

    rec2 = obs_events.FlightRecorder()
    empty = Checkpointer(tmp_path / "none", recorder=rec2)
    try:
        assert empty.restore_latest_valid(state) is None
    finally:
        empty.close()
    assert [e.kind for e in rec2.events()] == ["ckpt.restore_miss"]


def test_elastic_recovery_events_and_give_up_black_box(tmp_path):
    from distributed_tensorflow_guide_tpu.train.checkpoint import (
        Checkpointer,
    )
    from distributed_tensorflow_guide_tpu.train.elastic import (
        TooManyRestarts,
        run_with_recovery,
    )

    def step_fn(state, batch):
        return {"params": state["params"] - 0.01 * batch}, {}

    def make_data(start):
        return (jnp.full((4,), float(s)) for s in range(start, 10_000))

    crashed = []

    def crashing(state, batch):
        if int(batch[0]) == 7 and not crashed:
            crashed.append(True)
            raise RuntimeError("injected crash")
        return step_fn(state, batch)

    rec = obs_events.FlightRecorder()
    prev = obs_events.install(rec)
    ckpt = Checkpointer(tmp_path / "el", max_to_keep=2)
    try:
        run_with_recovery(crashing, {"params": jnp.ones((4,))},
                          make_data, ckpt,
                          hooks=[StopAtStepHook(10)],
                          checkpoint_every=5, max_restarts=3)
    finally:
        obs_events.install(prev)
        ckpt.close()
    el = [e for e in rec.events() if e.kind.startswith("elastic.")]
    assert [e.kind for e in el] == \
        ["elastic.restore", "elastic.restart", "elastic.restore"]
    assert el[0].payload == {"start": 0, "restarts": 0, "fresh": True}
    assert el[1].payload == {"step": 7, "restarts": 1,
                             "error": "RuntimeError"}
    assert el[2].payload == {"start": 5, "restarts": 1, "fresh": False}
    # the restore ladder's choices landed too (save at 5, 10 + end save)
    assert "ckpt.restore" in {e.kind for e in rec.events()}

    # restart budget exhausted -> elastic.give_up crash-dumps the tail
    bb = tmp_path / "giveup.json"
    rec2 = obs_events.FlightRecorder(crash_dump_path=str(bb))
    prev = obs_events.install(rec2)
    ckpt2 = Checkpointer(tmp_path / "fail", max_to_keep=1)
    try:
        with pytest.raises(TooManyRestarts):
            run_with_recovery(
                lambda s, b: (_ for _ in ()).throw(RuntimeError("perm")),
                {"params": jnp.ones((4,))}, make_data, ckpt2,
                hooks=[StopAtStepHook(10)], checkpoint_every=5,
                max_restarts=1)
    finally:
        obs_events.install(prev)
        ckpt2.close()
    dumped = json.loads(bb.read_text())
    last = dumped["events"][-1]
    assert last["kind"] == "elastic.give_up"
    # the counter has moved past the budget when the supervisor quits
    assert last["payload"]["restarts"] == 2
    assert last["payload"]["error"] == "RuntimeError"


def test_anomaly_trip_events():
    from distributed_tensorflow_guide_tpu.train.anomaly import (
        AnomalyDetected,
        AnomalySentinelHook,
    )

    rec = obs_events.FlightRecorder()
    data = iter([jnp.ones((4,)), jnp.full((4,), jnp.nan)])

    def step(state, batch):
        return state, {"loss": jnp.sum(batch)}

    loop = TrainLoop(step, {"w": jnp.zeros(2)}, data,
                     hooks=[AnomalySentinelHook(budget=3, recorder=rec)])
    with pytest.raises(AnomalyDetected):
        loop.run()
    trips = [e for e in rec.events() if e.kind == "anomaly.trip"]
    assert len(trips) == 1
    assert trips[0].payload["step"] == 1
    assert trips[0].payload["trips"] == 1
    assert trips[0].payload["budget"] == 3


# ---- cost reconciliation: pinned closed form --------------------------------


def test_reconcile_closed_form():
    roof = obs_recon.Roofline(peak_flops_s=100.0, peak_hbm_bytes_s=50.0,
                              peak_ici_bytes_s=10.0)
    cost = {"flops": 200.0, "hbm_bytes_read": 70.0,
            "hbm_bytes_written": 50.0, "collective_bytes": {"data": 5.0}}
    r = obs_recon.reconcile(cost, 4.0, roof)
    assert r["achieved_gflops_s"] == pytest.approx(200 / 4 / 1e9)
    assert r["achieved_hbm_gb_s"] == pytest.approx(120 / 4 / 1e9)
    assert r["achieved_ici_gb_s"] == pytest.approx(5 / 4 / 1e9)
    assert r["flops_frac"] == pytest.approx(0.5)      # 200/4/100
    assert r["hbm_frac"] == pytest.approx(0.6)        # 120/4/50
    assert r["ici_frac"] == pytest.approx(0.125)      # 5/4/10
    # model time = max(200/100, 120/50, 5/10) = 2.4s -> memory-bound
    assert r["model_time_s"] == pytest.approx(2.4)
    assert r["efficiency"] == pytest.approx(0.6)
    assert r["bound"] == "memory"
    # no ICI peak: comm drops out of the roofline entirely
    r2 = obs_recon.reconcile(cost, 4.0, obs_recon.Roofline(100.0, 50.0))
    assert r2["ici_frac"] is None and r2["bound"] == "memory"
    with pytest.raises(ValueError, match="measured_s"):
        obs_recon.reconcile(cost, 0.0, roof)


def test_roofline_from_env(monkeypatch):
    monkeypatch.setenv("DTG_PEAK_FLOPS", "1e12")
    monkeypatch.setenv("DTG_PEAK_HBM_BPS", "1e11")
    monkeypatch.setenv("DTG_PEAK_ICI_BPS", "1e10")
    roof = obs_recon.Roofline.from_env()
    assert roof.peak_flops_s == 1e12
    assert roof.peak_hbm_bytes_s == 1e11
    assert roof.peak_ici_bytes_s == 1e10
    monkeypatch.delenv("DTG_PEAK_ICI_BPS")
    assert obs_recon.Roofline.from_env().peak_ici_bytes_s is None

# ---- fleet reliability plane (PR 20) ----------------------------------------


def test_absorb_fleet_shapes_from_real_health(params):
    """The ``dtg_fleet_*`` reliability series, shape-tested against a
    REAL ``FleetScheduler.health()`` driven through a crash + stall
    storm — not a hand-built dict — so the absorber and the health
    schema cannot drift apart.  The storm's recovery lifecycle must
    also land in the flight recorder as ``fleet.*`` events."""
    from distributed_tensorflow_guide_tpu.serve import FleetScheduler
    from distributed_tensorflow_guide_tpu.testing.chaos import Fault

    rec = obs_events.FlightRecorder()
    fc = FaultSchedule([Fault("replica_crash", 3, 0.0),
                        Fault("migration_torn", 3),
                        Fault("replica_stall", 6, 1.0)])
    fl = FleetScheduler(CFG, params, replicas=2, slots=2, num_blocks=33,
                        block_size=8, prefill_chunk=8, temperature=0.8,
                        top_k=10, fleet_chaos=fc, recorder=rec)
    for i, (p, mn) in enumerate(zip(PROMPTS, MAX_NEW)):
        fl.submit(Request(rid=i, prompt=p, max_new_tokens=mn,
                          rng=jax.random.PRNGKey(100 + i), tenant=i % 2))
    fl.run()
    h = fl.health()
    kinds = {str(e.kind) for e in rec.events()}
    assert {"fleet.replica_crash", "fleet.replica_stall",
            "fleet.migration_torn", "fleet.migrate_dup",
            "fleet.replica_probe",
            "fleet.replica_recovered"} <= kinds

    reg = obs_metrics.Registry()
    obs_metrics.absorb_fleet(reg, h)
    snap = reg.snapshot()
    assert snap["dtg_fleet_replica_crashes_total"] == 1
    assert snap["dtg_fleet_replica_stalls_total"] == 1
    assert snap["dtg_fleet_migration_dups_dropped_total"] == 1
    assert snap["dtg_fleet_breaker_probes_total"] >= 1
    assert snap["dtg_fleet_breaker_recoveries_total"] >= 1
    assert snap["dtg_fleet_completed_total"] == 3
    assert snap["dtg_fleet_stalled_replicas"] == 0
    assert snap["dtg_fleet_draining_replicas"] == 0
    assert snap["dtg_fleet_autoscale_target"] == 2
    # per-replica reliability gauges under {replica, role} labels
    assert snap['dtg_fleet_replica_breaker_open'
                '{replica="0",role="colocated"}'] == 0.0
    assert snap['dtg_fleet_replica_breaker_open'
                '{replica="1",role="colocated"}'] == 0.0
    assert 'dtg_fleet_replica_launch_failures_total' \
        '{replica="0",role="colocated"}' in snap
    # the engine-level attempt counter rolls up separately from the
    # fleet-level step-boundary fault counter
    assert "dtg_fleet_launch_failures_total" in snap
    assert "dtg_fleet_replica_faults_total" in snap
    fl.check_leaks()
    fl.close()
