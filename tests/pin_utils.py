"""Thin shims over the analysis walker (kept for import stability).

Round 9 consolidated the duplicated per-test jaxpr scanners here; round 13
promoted them into the library proper as
``distributed_tensorflow_guide_tpu.analysis.walker`` — the sub-jaxpr-
complete traversal the contract linter is built on (which also fixes this
module's old blind spots: dict-valued eqn params and ``eqn.invars``
aliasing; see tests/test_analysis.py for the positive controls). Tests
import from the package directly now; these re-exports stay so any
out-of-tree user of the old names keeps working.
"""

from distributed_tensorflow_guide_tpu.analysis.walker import (  # noqa: F401
    count_primitives,
    max_f32_elems_with_vocab_dim,
    traced_text,
)
