"""Shared jaxpr/trace-scanning pin helpers.

Consolidates the duplicated scanners from tests/test_fused_ce.py and
tests/test_autotune.py (round 9) so every structural pin — the fused-CE
no-full-logits walk, the overlap layer's byte-identical-trace pin — uses
one implementation.
"""

import jax
import jax.numpy as jnp
import numpy as np
from jax.extend import core as jex_core


def max_f32_elems_with_vocab_dim(jaxpr, n, v):
    """Largest f32 intermediate of shape (..., V) with >= n rows, walked
    through every sub-jaxpr (scan/pjit/custom_vjp bodies included)."""
    if isinstance(jaxpr, jex_core.ClosedJaxpr):
        jaxpr = jaxpr.jaxpr
    worst = 0
    for eqn in jaxpr.eqns:
        for var in eqn.outvars:
            aval = var.aval
            shape = getattr(aval, "shape", ())
            if (getattr(aval, "dtype", None) == jnp.float32
                    and len(shape) >= 2 and shape[-1] == v
                    and int(np.prod(shape[:-1])) >= n):
                worst = max(worst, int(np.prod(shape)))
        for p in eqn.params.values():
            for sub in (p if isinstance(p, (tuple, list)) else (p,)):
                if isinstance(sub, (jex_core.Jaxpr, jex_core.ClosedJaxpr)):
                    worst = max(
                        worst, max_f32_elems_with_vocab_dim(sub, n, v))
    return worst


def count_primitives(jaxpr, name: str) -> int:
    """Occurrences of one primitive across the jaxpr and every sub-jaxpr
    — e.g. how many ``psum`` binds a bucketed backward emits."""
    if isinstance(jaxpr, jex_core.ClosedJaxpr):
        jaxpr = jaxpr.jaxpr
    n = 0
    for eqn in jaxpr.eqns:
        if eqn.primitive.name == name:
            n += 1
        for p in eqn.params.values():
            for sub in (p if isinstance(p, (tuple, list)) else (p,)):
                if isinstance(sub, (jex_core.Jaxpr, jex_core.ClosedJaxpr)):
                    n += count_primitives(sub, name)
    return n


def traced_text(fn, *args) -> str:
    """The full textual trace of ``fn`` at ``args`` (every sub-jaxpr
    printed) — the byte-identity instrument: two code paths that must
    trace the same program compare equal here. Variable naming is
    deterministic within a process, so equal programs compare equal and
    any structural drift shows as a diff. Raw object addresses (repr'd
    closures/meshes in eqn params) are normalized away — they differ per
    Python instance, not per program."""
    import re

    return re.sub(r"0x[0-9a-f]+", "0x•", str(jax.make_jaxpr(fn)(*args)))
