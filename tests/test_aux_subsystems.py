"""Aux-subsystem tests: config system, determinism checker, TensorBoard
writer, profiler hook (SURVEY.md §5 rows)."""

import dataclasses
import struct

import jax
import jax.numpy as jnp
import pytest

from distributed_tensorflow_guide_tpu.core.config import RunConfig
from distributed_tensorflow_guide_tpu.core.mesh import MeshSpec
from distributed_tensorflow_guide_tpu.utils.determinism import (
    check_runs,
    check_topologies,
)
from distributed_tensorflow_guide_tpu.utils.tb_writer import (
    SummaryWriter,
    read_scalars,
    _crc32c,
)


# -- config ------------------------------------------------------------------


def test_config_roundtrip_json(tmp_path):
    cfg = RunConfig(mesh=MeshSpec(data=2, model=4), steps=7, lr=0.5,
                    ckpt_dir=str(tmp_path / "ck"))
    p = tmp_path / "run.json"
    cfg.save(p)
    assert RunConfig.load(p) == cfg


def test_config_from_argv_defaults_and_overrides():
    cfg = RunConfig.from_argv([])
    assert cfg == RunConfig()
    cfg = RunConfig.from_argv(
        ["--steps", "42", "--lr", "0.01", "--mesh-model", "2",
         "--mesh-data", "-1", "--tb-logdir", "/tmp/tb"])
    assert cfg.steps == 42 and cfg.lr == 0.01
    assert cfg.mesh == MeshSpec(data=-1, model=2)
    assert cfg.tb_logdir == "/tmp/tb"
    assert cfg.ckpt_dir is None


def test_config_rejects_unknown_keys():
    with pytest.raises(ValueError, match="unknown"):
        RunConfig.from_dict({"stepz": 1})


def test_config_field_coverage():
    # every field is settable from the CLI (guards against drift)
    names = {f.name for f in dataclasses.fields(RunConfig)} - {"mesh"}
    parser = RunConfig.parser()
    dests = {a.dest for a in parser._actions}
    assert names <= dests


# -- determinism checker -----------------------------------------------------


def _toy_train(seed: int):
    key = jax.random.PRNGKey(seed)
    w = jax.random.normal(key, (4,))
    out = []
    for step in range(3):
        loss = float(jnp.sum(w**2) * (step + 1))
        out.append({"loss": loss})
    return out


def test_check_runs_passes_for_deterministic_fn():
    rep = check_runs(_toy_train, seed=3, runs=3)
    assert rep.ok and rep.max_abs_diff == 0.0


def test_check_runs_catches_nondeterminism():
    state = {"n": 0}

    def flaky(seed):
        state["n"] += 1
        return [{"loss": 1.0 + 0.1 * state["n"]}]

    rep = check_runs(flaky, runs=2)
    assert not rep.ok
    with pytest.raises(AssertionError):
        rep.raise_if_failed()


def test_check_runs_fails_on_one_sided_nan():
    state = {"n": 0}

    def diverges_once(seed):
        state["n"] += 1
        return [{"loss": float("nan") if state["n"] == 2 else 1.0}]

    rep = check_runs(diverges_once, runs=2)
    assert not rep.ok and "NaN" in rep.detail


def test_check_topologies_tolerance():
    def train(spec: MeshSpec, seed: int):
        # topology-independent math with tiny fake jitter below rtol
        eps = 1e-7 if spec.model > 1 else 0.0
        return [{"loss": 1.0 + eps}]

    rep = check_topologies(
        train, [MeshSpec(data=-1), MeshSpec(data=-1, model=2)], rtol=1e-5)
    assert rep.ok
    rep = check_topologies(
        train, [MeshSpec(data=-1), MeshSpec(data=-1, model=2)], rtol=1e-9)
    assert not rep.ok


# -- TensorBoard writer ------------------------------------------------------


def test_crc32c_known_vectors():
    # RFC 3720 test vectors
    assert _crc32c(b"") == 0x0
    assert _crc32c(b"123456789") == 0xE3069283
    assert _crc32c(bytes(32)) == 0x8A9136AA


def test_tb_roundtrip(tmp_path):
    with SummaryWriter(tmp_path) as w:
        w.scalars(1, {"loss": 2.5, "acc": 0.125})
        w.scalars(2, {"loss": 1.25})
    files = list(tmp_path.glob("events.out.tfevents.*"))
    assert len(files) == 1
    events = read_scalars(files[0])
    assert events == [(1, {"loss": 2.5, "acc": 0.125}), (2, {"loss": 1.25})]


def test_tb_file_structure_valid_tfrecord(tmp_path):
    with SummaryWriter(tmp_path) as w:
        w.scalars(5, {"x": 1.0})
    raw = next(tmp_path.glob("events.*")).read_bytes()
    (ln,) = struct.unpack_from("<Q", raw, 0)
    first = raw[12:12 + ln]
    # first record is the file_version event: field 3, "brain.Event:2"
    assert b"brain.Event:2" in first


def test_tb_truncated_tail_reads_complete_prefix(tmp_path):
    with SummaryWriter(tmp_path) as w:
        w.scalars(1, {"x": 1.0})
        w.scalars(2, {"x": 2.0})
    f = next(tmp_path.glob("events.*"))
    raw = f.read_bytes()
    f.write_bytes(raw[:-7])  # SIGKILL mid-write of the last record
    assert read_scalars(f) == [(1, {"x": 1.0})]


def test_tb_corruption_detected(tmp_path):
    with SummaryWriter(tmp_path) as w:
        w.scalars(1, {"x": 1.0})
    f = next(tmp_path.glob("events.*"))
    raw = bytearray(f.read_bytes())
    raw[-6] ^= 0xFF  # flip a payload byte of the last record
    f.write_bytes(bytes(raw))
    with pytest.raises(ValueError, match="corrupt"):
        read_scalars(f)


# -- TensorBoardHook + ProfilerHook through a real loop ----------------------


def test_tb_hook_in_train_loop(tmp_path):
    from distributed_tensorflow_guide_tpu.train.hooks import TensorBoardHook

    hook = TensorBoardHook(tmp_path, every_steps=2)

    class FakeLoop:
        step = 0

    hook.begin(FakeLoop())
    for s in range(4):
        hook.after_step(s, {"loss": float(s)})
    hook.end(4)
    events = read_scalars(next(tmp_path.glob("events.*")))
    assert [s for s, _ in events] == [0, 2]


def test_profiler_hook_writes_trace(tmp_path):
    from distributed_tensorflow_guide_tpu.utils.profiling import ProfilerHook

    hook = ProfilerHook(tmp_path, start_step=2, end_step=4)
    for s in range(6):
        jnp.sum(jnp.ones(8)).block_until_ready()
        hook.after_step(s, {})
    hook.end(6)
    assert not hook._active
    # jax.profiler.trace writes plugins/profile/<run>/ under the logdir
    assert list(tmp_path.rglob("*.xplane.pb")), "no xplane trace written"


def test_profiler_hook_start_step_zero(tmp_path):
    from distributed_tensorflow_guide_tpu.utils.profiling import ProfilerHook

    hook = ProfilerHook(tmp_path, start_step=0, end_step=2)

    class FakeLoop:
        step = 0

    hook.begin(FakeLoop())
    assert hook._active
    for s in range(3):
        jnp.sum(jnp.ones(8)).block_until_ready()
        hook.after_step(s, {})
    assert not hook._active
    assert list(tmp_path.rglob("*.xplane.pb"))


def test_profiler_hook_stops_on_early_end(tmp_path):
    from distributed_tensorflow_guide_tpu.utils.profiling import ProfilerHook

    hook = ProfilerHook(tmp_path, start_step=1, end_step=100)
    hook.after_step(0, {})
    assert hook._active
    hook.end(1)
    assert not hook._active


# ---- the determinism gate (reference R2 control discipline) -----------------
# One command reproduces the reference's control-vs-distributed diff: the
# examples/non_distributed.py trainer is the oracle, and the same training
# run under several mesh topologies must match it (SURVEY.md §4 item 3).


def test_mnist_topology_determinism_gate():
    import sys
    from pathlib import Path

    sys.path.insert(0, str(Path(__file__).resolve().parents[1]))
    import optax
    from flax.training import train_state

    from distributed_tensorflow_guide_tpu.core.mesh import MeshSpec, build_mesh
    from distributed_tensorflow_guide_tpu.data.synthetic import synthetic_mnist
    from distributed_tensorflow_guide_tpu.models.mnist_cnn import (
        MNISTCNN,
        make_loss_fn,
    )
    from distributed_tensorflow_guide_tpu.parallel.data_parallel import (
        DataParallel,
    )
    from distributed_tensorflow_guide_tpu.utils.determinism import (
        check_topologies,
    )
    from examples.non_distributed import train as control_train

    STEPS, BATCH, LR, SEED = 5, 32, 0.05, 0

    def dp_train(topo, seed: int):
        spec, accum, kind = topo
        mesh = build_mesh(spec, devices=jax.devices()[:spec.data])
        model = MNISTCNN()
        params = model.init(
            jax.random.PRNGKey(seed), jnp.zeros((1, 28, 28, 1))
        )["params"]
        loss_fn = make_loss_fn(model)
        if kind == "fsdp":
            from jax.sharding import NamedSharding, PartitionSpec as P

            from distributed_tensorflow_guide_tpu.parallel.fsdp import FSDP

            fsdp = FSDP(mesh, min_shard_size=2 ** 10)
            shardings = fsdp.param_shardings(params)
            params = jax.device_put(params, shardings)
            state = train_state.TrainState.create(
                apply_fn=model.apply, params=params,
                tx=optax.sgd(LR, momentum=0.9),
            )
            st_sh = fsdp.state_shardings(state, shardings)
            state = jax.device_put(state, st_sh)
            step = fsdp.make_train_step(loss_fn, st_sh, donate=False)
            shard = lambda b: jax.device_put(  # noqa: E731
                b, NamedSharding(mesh, P("data"))
            )
        else:
            dp = DataParallel(mesh)
            state = dp.replicate(train_state.TrainState.create(
                apply_fn=model.apply, params=params,
                tx=optax.sgd(LR, momentum=0.9),
            ))
            step = dp.make_train_step(loss_fn, donate=False,
                                      accum_steps=accum)
            shard = dp.shard_batch
        out = []
        for b in synthetic_mnist(BATCH, seed=seed).take(STEPS):
            state, m = step(state, shard(b))
            out.append({k: float(v) for k, v in m.items()})
        return out

    # same seed, same global batch; topologies: full-mesh DP, 2-way DP,
    # 4-way DP with 2-step gradient accumulation (mean-of-means ==
    # full-batch mean at equal microbatch sizes), and fully-sharded
    # (ZeRO-3) over 8 — an execution-layout change that must not move
    # the numbers
    specs = [(MeshSpec(data=8), 1, "dp"), (MeshSpec(data=2), 1, "dp"),
             (MeshSpec(data=4), 2, "dp"), (MeshSpec(data=8), 1, "fsdp")]
    rep = check_topologies(dp_train, specs, seed=SEED, rtol=1e-4)
    rep.raise_if_failed()

    # and all of them must match the single-device control trainer
    control = control_train(STEPS, BATCH, LR, seed=SEED)
    dp8 = dp_train(specs[0], SEED)
    for c, d in zip(control, dp8):
        assert abs(c["loss"] - d["loss"]) <= 1e-4 * max(abs(c["loss"]), 1e-12)
