"""Serving subsystem (serve/): the acceptance pin is BITWISE parity —
every per-request stream the continuous-batching engine emits must be
identical to a one-shot ``make_generate_fn`` run of that request alone,
greedy and sampled, across the decode levers, through chunked prefill,
and across eviction/re-admission. Plus the host-side invariants the
device programs rest on: block accounting (no leak, no aliasing),
deterministic scheduling under a fixed trace, and the paged byte model.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_tensorflow_guide_tpu.models.generation import (
    decode_cache_bytes_per_step,
    make_generate_fn,
    paged_decode_cache_bytes_per_step,
)
from distributed_tensorflow_guide_tpu.models.transformer import (
    Transformer,
    TransformerConfig,
)
from distributed_tensorflow_guide_tpu.ops.decode_attention import (
    cache_slot_bytes,
)
from distributed_tensorflow_guide_tpu.serve import (
    BlockPool,
    Request,
    ServeEngine,
    blocks_for,
    build_step_fns,
    gather_view,
    scatter_chunk,
    table_row,
)

CFG = TransformerConfig(vocab_size=64, num_layers=2, num_heads=2,
                        d_model=16, d_ff=32, max_len=64, causal=True,
                        dtype=jnp.float32)

PROMPTS = [np.array([3, 5, 7, 9, 11], np.int32),
           np.array([2, 4, 6, 8, 10, 12, 14, 16, 18], np.int32),
           np.array([1] * 17, np.int32)]
MAX_NEW = [8, 6, 10]


@pytest.fixture(scope="module")
def params():
    return Transformer(CFG).init(
        jax.random.PRNGKey(0), jnp.zeros((2, 8), jnp.int32))["params"]


def _oracle(cfg, params, i, temp, top_k, *, prompts=PROMPTS,
            max_new=MAX_NEW, **gen_kw):
    """The one-shot stream request ``i`` must reproduce bitwise."""
    p, mn = prompts[i], max_new[i]
    gen = make_generate_fn(cfg, max_new_tokens=mn, temperature=temp,
                           top_k=top_k, **gen_kw)
    out = gen(params, p[None], jax.random.PRNGKey(100 + i))
    return np.asarray(out)[0, len(p):].tolist()


def _serve(cfg, params, *, temp, top_k, prompts=PROMPTS, max_new=MAX_NEW,
           **kw):
    eng = ServeEngine(cfg, params, temperature=temp, top_k=top_k, **kw)
    for i, (p, mn) in enumerate(zip(prompts, max_new)):
        eng.submit(Request(rid=i, prompt=p, max_new_tokens=mn,
                           rng=jax.random.PRNGKey(100 + i)))
    events = eng.run()
    return eng, events


# ---- the acceptance pin: engine == one-shot, bitwise ------------------------


@pytest.mark.parametrize("temp,top_k", [(0.0, None), (0.8, 10)],
                         ids=["greedy", "sampled"])
def test_engine_matches_one_shot_bitwise(params, temp, top_k):
    """Three mixed-length requests on two slots: every completed stream
    equals that request's solo one-shot run exactly — positions-derived
    sampling keys make the engine's interleaving invisible."""
    eng, events = _serve(CFG, params, temp=temp, top_k=top_k, slots=2,
                         num_blocks=33, block_size=8, prefill_chunk=8)
    got = eng.completions()
    for i in range(len(PROMPTS)):
        assert got[i] == _oracle(CFG, params, i, temp, top_k), f"req {i}"
    assert eng.sched.done == {0, 1, 2}
    # every rid emits exactly one first and one done event
    assert sorted(e.rid for e in events if e.first) == [0, 1, 2]
    assert sorted(e.rid for e in events if e.done) == [0, 1, 2]
    eng.sched.pool.check_leaks()
    assert eng.live_blocks() == 0


def test_chunked_prefill_equals_whole_prompt(params):
    """prefill_chunk=8 (longest prompt streams in 3 chunks, interleaved
    with decode) vs prefill_chunk=32 (every prompt is one chunk): the
    completions must be identical token for token — the chunk schedule
    only changes WHEN cache rows get written, never what is sampled."""
    chunked, _ = _serve(CFG, params, temp=0.8, top_k=10, slots=2,
                        num_blocks=33, block_size=8, prefill_chunk=8)
    whole, _ = _serve(CFG, params, temp=0.8, top_k=10, slots=2,
                      num_blocks=33, block_size=8, prefill_chunk=32)
    assert chunked.completions() == whole.completions()
    # chunked really did split: more prefill launches than requests
    assert chunked.steps["prefill"] > len(PROMPTS)
    assert whole.steps["prefill"] == len(PROMPTS)


@pytest.mark.parametrize("kv,impl", [("int8", "dense"), (None, "pallas"),
                                     ("int8", "pallas")])
def test_engine_parity_across_decode_levers(params, kv, impl):
    """The serving path reuses the one-shot decode levers (int8 KV pool,
    length-aware paged Pallas kernel) — parity must hold bitwise under
    each, because engine and oracle run the SAME lever code."""
    cfg = dataclasses.replace(CFG, kv_dtype=kv, decode_impl=impl)
    prompts, max_new = PROMPTS[:2], MAX_NEW[:2]
    eng, _ = _serve(cfg, params, temp=0.8, top_k=10, prompts=prompts,
                    max_new=max_new, slots=2, num_blocks=17,
                    block_size=8, prefill_chunk=8)
    got = eng.completions()
    for i in range(len(prompts)):
        assert got[i] == _oracle(cfg, params, i, 0.8, 10,
                                 prompts=prompts, max_new=max_new), \
            f"req {i} kv={kv} impl={impl}"
    eng.sched.pool.check_leaks()


def test_speculative_one_shot_equals_engine_stream(params):
    """The engine never drafts; the speculative lever is covered through
    the spec==vanilla guarantee: a one-shot run WITH self-speculation
    emits the vanilla stream bitwise, and the engine emits the vanilla
    stream bitwise, so the two agree (docs/serving.md rationale)."""
    spec_oracle = _oracle(CFG, params, 0, 0.7, 12, spec_draft_layers=1)
    eng, _ = _serve(CFG, params, temp=0.7, top_k=12,
                    prompts=PROMPTS[:1], max_new=MAX_NEW[:1], slots=2,
                    num_blocks=17, block_size=8, prefill_chunk=8)
    assert eng.completions()[0] == spec_oracle


def test_eviction_preemption_preserves_parity(params):
    """A pool too small for both residents forces preemption mid-decode;
    the evicted request's continuation (prompt + emitted tail, remaining
    budget, same rng) re-prefills and must land on the SAME stream —
    eviction can never fork a request."""
    prompts = [np.array([3, 5, 7, 9, 11], np.int32),
               np.array([2, 4, 6, 8, 10, 12, 14], np.int32)]
    max_new = [40, 40]
    # capacity 8 blocks x 8 slots = 64 positions < the ~92 both need
    eng, _ = _serve(CFG, params, temp=0.7, top_k=12, prompts=prompts,
                    max_new=max_new, slots=2, num_blocks=9,
                    block_size=8, prefill_chunk=8)
    assert eng.sched.preemptions >= 1
    got = eng.completions()
    for i in range(2):
        assert got[i] == _oracle(CFG, params, i, 0.7, 12,
                                 prompts=prompts, max_new=max_new), \
            f"req {i} diverged across eviction"
    eng.sched.pool.check_leaks()
    assert eng.live_blocks() == 0


def test_mid_flight_admission_interleaves_streams(params):
    """Three requests, two slots: the third is admitted the moment a slot
    frees, WHILE the other resident keeps decoding — its tokens appear
    between the survivor's tokens with nothing recompiled."""
    eng, events = _serve(CFG, params, temp=0.0, top_k=None,
                         max_new=[16, 4, 6], slots=2, num_blocks=33,
                         block_size=8, prefill_chunk=8)
    first2 = next(k for k, e in enumerate(events)
                  if e.rid == 2 and e.first)
    first_done = next(k for k, e in enumerate(events) if e.done)
    assert first2 > first_done  # admitted into a freed slot...
    # ...while an earlier request was still streaming
    assert any(e.rid != 2 for e in events[first2 + 1:])
    assert eng.sched.done == {0, 1, 2}


def test_scheduler_determinism_replays_identical_event_log(params):
    """Identical submitted trace -> identical event log, tick for tick,
    including through preemption (the tight pool from the eviction test).
    Everything downstream (bench numbers, battery rows) rests on this."""
    prompts = [np.array([3, 5, 7, 9, 11], np.int32),
               np.array([2, 4, 6, 8, 10, 12, 14], np.int32)]
    max_new = [40, 40]

    def once():
        eng, events = _serve(CFG, params, temp=0.7, top_k=12,
                             prompts=prompts, max_new=max_new, slots=2,
                             num_blocks=9, block_size=8, prefill_chunk=8)
        return ([(e.rid, e.token, e.first, e.done) for e in events],
                dict(eng.steps), eng.sched.preemptions)

    log1, steps1, pre1 = once()
    log2, steps2, pre2 = once()
    assert log1 == log2
    assert steps1 == steps2 and pre1 == pre2


# ---- intake validation ------------------------------------------------------


def test_submit_validation(params):
    # capacity 4 blocks = 32 positions (the trash block is never granted)
    eng = ServeEngine(CFG, params, slots=2, num_blocks=5, block_size=8,
                      prefill_chunk=8)
    with pytest.raises(ValueError, match="out of vocabulary"):
        eng.submit(Request(rid=0, prompt=np.array([99], np.int32),
                           max_new_tokens=4, rng=jax.random.PRNGKey(0)))
    with pytest.raises(ValueError, match="empty prompt"):
        eng.submit(Request(rid=1, prompt=np.array([], np.int32),
                           max_new_tokens=4, rng=jax.random.PRNGKey(0)))
    with pytest.raises(ValueError, match="exceeds"):
        eng.submit(Request(rid=2, prompt=np.array([1] * 60, np.int32),
                           max_new_tokens=8, rng=jax.random.PRNGKey(0)))
    with pytest.raises(ValueError, match="never fit"):
        # fits max_len (38 <= 64) but needs 5 blocks, capacity 4
        eng.submit(Request(rid=3, prompt=np.array([1] * 30, np.int32),
                           max_new_tokens=8, rng=jax.random.PRNGKey(0)))
    with pytest.raises(ValueError, match="must divide"):
        ServeEngine(CFG, params, slots=2, num_blocks=9, block_size=8,
                    prefill_chunk=7)


# ---- host-side block accounting ---------------------------------------------


def test_block_pool_accounting():
    pool = BlockPool(5, 8)
    assert pool.trash_block == 4 and pool.capacity == 4
    # lowest ids first, deterministically
    assert pool.alloc(1, 2) == [0, 1]
    # an unsatisfiable alloc changes nothing
    assert pool.alloc(2, 3) is None and pool.free_blocks == 2
    assert pool.alloc(2, 2) == [2, 3]  # trash block never handed out
    assert pool.live_blocks() == 4 and pool.owned_by(1) == [0, 1]
    pool.check_leaks()
    # ownership is enforced on free: no cross-request free, no double free
    with pytest.raises(ValueError, match="does not own"):
        pool.free(2, [0])
    pool.free(1, [0, 1])
    with pytest.raises(ValueError, match="does not own"):
        pool.free(1, [0, 1])
    assert pool.alloc(3, 1) == [0]  # freed blocks recycle lowest-first
    pool.check_leaks()
    # a leaked block is caught
    del pool._owner[0]
    with pytest.raises(AssertionError, match="leak"):
        pool.check_leaks()
    with pytest.raises(ValueError, match=">= 2 blocks"):
        BlockPool(1, 8)


def test_blocks_for_and_table_row():
    assert blocks_for(1, 8) == 1
    assert blocks_for(8, 8) == 1
    assert blocks_for(9, 8) == 2
    row = table_row([3, 1], 4, trash=9)
    np.testing.assert_array_equal(row, [3, 1, 9, 9])
    np.testing.assert_array_equal(table_row([], 3, trash=5), [5, 5, 5])


# ---- device-side gather / scatter -------------------------------------------


def test_gather_scatter_roundtrip_and_trash_isolation():
    """scatter_chunk through a table then gather_view back must equal the
    dense view, and a trash-pointing table row must leave every owned
    block untouched (the inactive-slot write path)."""
    r = np.random.RandomState(0)
    N, bs, H, hd = 5, 4, 2, 3  # legacy (B, S, H, hd) layout: seq_axis 1
    pool = jnp.asarray(r.randn(N, bs, H, hd), jnp.float32)
    tables = jnp.asarray([[2, 0, 3], [4, 4, 4]], jnp.int32)  # trash id 4
    view = gather_view(pool, tables, seq_axis=1)
    assert view.shape == (2, 3 * bs, H, hd)
    np.testing.assert_array_equal(
        np.asarray(view[0, :bs]), np.asarray(pool[2]))
    np.testing.assert_array_equal(
        np.asarray(view[1, bs:2 * bs]), np.asarray(pool[4]))
    # write a 4-token chunk for request 0 at logical position 2 (straddles
    # physical blocks 2 and 0) while request 1's row points at trash
    chunk = jnp.asarray(r.randn(2, 4, H, hd), jnp.float32)
    idx = jnp.asarray([2, 0], jnp.int32)
    out = scatter_chunk(pool, chunk, tables, idx, block_size=bs,
                        seq_axis=1)
    got = gather_view(out, tables, seq_axis=1)
    np.testing.assert_array_equal(np.asarray(got[0, 2:6]),
                                  np.asarray(chunk[0]))
    # request 0's untouched positions survive
    np.testing.assert_array_equal(np.asarray(got[0, :2]),
                                  np.asarray(view[0, :2]))
    np.testing.assert_array_equal(np.asarray(got[0, 6:]),
                                  np.asarray(view[0, 6:]))
    # request 1's trash-routed write left every unwritten block intact
    # (request 0 touched only physical blocks 2 and 0)
    np.testing.assert_array_equal(np.asarray(out[1]), np.asarray(pool[1]))
    np.testing.assert_array_equal(np.asarray(out[3]), np.asarray(pool[3]))


# ---- paged byte model -------------------------------------------------------


def test_paged_byte_model_charges_live_blocks_not_max_len():
    per_slot = CFG.num_heads * cache_slot_bytes(CFG.head_dim, CFG.dtype)
    got = paged_decode_cache_bytes_per_step(
        CFG, block_size=8, live_blocks=3, active_slots=2)
    assert got == CFG.num_layers * (3 * 8 + 2) * per_slot
    # strictly below the dense model's batch * max_len charge
    assert got < decode_cache_bytes_per_step(CFG, 2)
    # int8 pool: 1-byte slots + f32 scales through the shared definition
    i8 = paged_decode_cache_bytes_per_step(
        dataclasses.replace(CFG, kv_dtype="int8"), block_size=8,
        live_blocks=3, active_slots=2)
    assert i8 < got


# ---- program plumbing -------------------------------------------------------


def test_step_fns_donation_declared_and_gated():
    """The pool donation INTENT is always (1,) — the lint contract audits
    it in alias mode — but actual donation is gated off on the CPU test
    backend (no input-output aliasing there, same as make_generate_fn)."""
    fns = build_step_fns(CFG, slots=2, num_blocks=9, block_size=8,
                        prefill_chunk=8)
    assert fns.declared_donate_argnums == (1,)
    assert fns.donates_pool == (jax.default_backend() != "cpu")
    assert fns.cfg.paged_num_blocks == 9
    assert fns.n_blk == CFG.max_len // 8
    # memoized on everything that reaches the trace: a second engine at
    # the same geometry reuses the SAME jitted pair (slots / chunk width
    # shape-specialize inside jit and deliberately don't key the memo),
    # while a different pool geometry or sampling knob builds fresh
    assert build_step_fns(CFG, slots=4, num_blocks=9, block_size=8,
                          prefill_chunk=16) is fns
    assert build_step_fns(CFG, slots=2, num_blocks=17, block_size=8,
                          prefill_chunk=8) is not fns
    assert build_step_fns(CFG, slots=2, num_blocks=9, block_size=8,
                          prefill_chunk=8, temperature=0.5) is not fns
