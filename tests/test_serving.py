"""Serving subsystem (serve/): the acceptance pin is BITWISE parity —
every per-request stream the continuous-batching engine emits must be
identical to a one-shot ``make_generate_fn`` run of that request alone,
greedy and sampled, across the decode levers, through chunked prefill,
and across eviction/re-admission. Plus the host-side invariants the
device programs rest on: block accounting (no leak, no aliasing),
deterministic scheduling under a fixed trace, and the paged byte model.
"""

import dataclasses
import time
from types import SimpleNamespace

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_tensorflow_guide_tpu.models.generation import (
    decode_cache_bytes_per_step,
    make_generate_fn,
    paged_decode_cache_bytes_per_step,
)
from distributed_tensorflow_guide_tpu.models.transformer import (
    Transformer,
    TransformerConfig,
)
from distributed_tensorflow_guide_tpu.ops.decode_attention import (
    cache_slot_bytes,
)
from benchmarks.common import spill_bytes_per_swap
from distributed_tensorflow_guide_tpu.serve import (
    BlockPool,
    BlockStore,
    EngineOverloaded,
    Request,
    ServeEngine,
    blocks_for,
    build_step_fns,
    gather_view,
    scatter_chunk,
    table_row,
)
from distributed_tensorflow_guide_tpu.serve.scheduler import Scheduler, _Slot
from distributed_tensorflow_guide_tpu.testing.chaos import (
    Fault,
    FaultSchedule,
)

CFG = TransformerConfig(vocab_size=64, num_layers=2, num_heads=2,
                        d_model=16, d_ff=32, max_len=64, causal=True,
                        dtype=jnp.float32)

PROMPTS = [np.array([3, 5, 7, 9, 11], np.int32),
           np.array([2, 4, 6, 8, 10, 12, 14, 16, 18], np.int32),
           np.array([1] * 17, np.int32)]
MAX_NEW = [8, 6, 10]


@pytest.fixture(scope="module")
def params():
    return Transformer(CFG).init(
        jax.random.PRNGKey(0), jnp.zeros((2, 8), jnp.int32))["params"]


_ORACLE_CACHE: dict = {}  # every make_generate_fn call is a fresh compile


def _oracle(cfg, params, i, temp, top_k, *, prompts=PROMPTS,
            max_new=MAX_NEW, **gen_kw):
    """The one-shot stream request ``i`` must reproduce bitwise.

    Memoized: many tests pin against the same (cfg, request, sampling)
    oracle, and each uncached call compiles a whole one-shot program —
    the cache is most of this file's tier-1 wall-clock budget. Safe
    because every caller passes the module-scoped ``params`` fixture.
    """
    p, mn = prompts[i], max_new[i]
    key = (repr(cfg), i, temp, top_k, tuple(p.tolist()), mn,
           tuple(sorted(gen_kw.items())))
    if key not in _ORACLE_CACHE:
        gen = make_generate_fn(cfg, max_new_tokens=mn, temperature=temp,
                               top_k=top_k, **gen_kw)
        out = gen(params, p[None], jax.random.PRNGKey(100 + i))
        _ORACLE_CACHE[key] = np.asarray(out)[0, len(p):].tolist()
    return list(_ORACLE_CACHE[key])


def _serve(cfg, params, *, temp, top_k, prompts=PROMPTS, max_new=MAX_NEW,
           **kw):
    eng = ServeEngine(cfg, params, temperature=temp, top_k=top_k, **kw)
    for i, (p, mn) in enumerate(zip(prompts, max_new)):
        eng.submit(Request(rid=i, prompt=p, max_new_tokens=mn,
                           rng=jax.random.PRNGKey(100 + i)))
    events = eng.run()
    return eng, events


# ---- the acceptance pin: engine == one-shot, bitwise ------------------------


@pytest.mark.parametrize("temp,top_k", [(0.0, None), (0.8, 10)],
                         ids=["greedy", "sampled"])
def test_engine_matches_one_shot_bitwise(params, temp, top_k):
    """Three mixed-length requests on two slots: every completed stream
    equals that request's solo one-shot run exactly — positions-derived
    sampling keys make the engine's interleaving invisible."""
    eng, events = _serve(CFG, params, temp=temp, top_k=top_k, slots=2,
                         num_blocks=33, block_size=8, prefill_chunk=8)
    got = eng.completions()
    for i in range(len(PROMPTS)):
        assert got[i] == _oracle(CFG, params, i, temp, top_k), f"req {i}"
    assert eng.sched.done == {0, 1, 2}
    # every rid emits exactly one first and one done event
    assert sorted(e.rid for e in events if e.first) == [0, 1, 2]
    assert sorted(e.rid for e in events if e.done) == [0, 1, 2]
    eng.sched.pool.check_leaks()
    assert eng.live_blocks() == 0


def test_chunked_prefill_equals_whole_prompt(params):
    """prefill_chunk=8 (longest prompt streams in 3 chunks, interleaved
    with decode) vs prefill_chunk=32 (every prompt is one chunk): the
    completions must be identical token for token — the chunk schedule
    only changes WHEN cache rows get written, never what is sampled."""
    chunked, _ = _serve(CFG, params, temp=0.8, top_k=10, slots=2,
                        num_blocks=33, block_size=8, prefill_chunk=8)
    whole, _ = _serve(CFG, params, temp=0.8, top_k=10, slots=2,
                      num_blocks=33, block_size=8, prefill_chunk=32)
    assert chunked.completions() == whole.completions()
    # chunked really did split: more prefill launches than requests
    assert chunked.steps["prefill"] > len(PROMPTS)
    assert whole.steps["prefill"] == len(PROMPTS)


@pytest.mark.parametrize("kv,impl", [("int8", "dense"), (None, "pallas"),
                                     ("int8", "pallas")])
def test_engine_parity_across_decode_levers(params, kv, impl):
    """The serving path reuses the one-shot decode levers (int8 KV pool,
    length-aware paged Pallas kernel) — parity must hold bitwise under
    each, because engine and oracle run the SAME lever code."""
    cfg = dataclasses.replace(CFG, kv_dtype=kv, decode_impl=impl)
    prompts, max_new = PROMPTS[:2], MAX_NEW[:2]
    eng, _ = _serve(cfg, params, temp=0.8, top_k=10, prompts=prompts,
                    max_new=max_new, slots=2, num_blocks=17,
                    block_size=8, prefill_chunk=8)
    got = eng.completions()
    for i in range(len(prompts)):
        assert got[i] == _oracle(cfg, params, i, 0.8, 10,
                                 prompts=prompts, max_new=max_new), \
            f"req {i} kv={kv} impl={impl}"
    eng.sched.pool.check_leaks()


def test_speculative_one_shot_equals_engine_stream(params):
    """The engine never drafts; the speculative lever is covered through
    the spec==vanilla guarantee: a one-shot run WITH self-speculation
    emits the vanilla stream bitwise, and the engine emits the vanilla
    stream bitwise, so the two agree (docs/serving.md rationale)."""
    spec_oracle = _oracle(CFG, params, 0, 0.7, 12, spec_draft_layers=1)
    eng, _ = _serve(CFG, params, temp=0.7, top_k=12,
                    prompts=PROMPTS[:1], max_new=MAX_NEW[:1], slots=2,
                    num_blocks=17, block_size=8, prefill_chunk=8)
    assert eng.completions()[0] == spec_oracle


def test_eviction_preemption_preserves_parity(params):
    """A pool too small for both residents forces preemption mid-decode;
    the evicted request's continuation (prompt + emitted tail, remaining
    budget, same rng) re-prefills and must land on the SAME stream —
    eviction can never fork a request."""
    prompts = [np.array([3, 5, 7, 9, 11], np.int32),
               np.array([2, 4, 6, 8, 10, 12, 14], np.int32)]
    max_new = [40, 40]
    # capacity 8 blocks x 8 slots = 64 positions < the ~92 both need
    eng, _ = _serve(CFG, params, temp=0.7, top_k=12, prompts=prompts,
                    max_new=max_new, slots=2, num_blocks=9,
                    block_size=8, prefill_chunk=8)
    assert eng.sched.preemptions >= 1
    got = eng.completions()
    for i in range(2):
        assert got[i] == _oracle(CFG, params, i, 0.7, 12,
                                 prompts=prompts, max_new=max_new), \
            f"req {i} diverged across eviction"
    eng.sched.pool.check_leaks()
    assert eng.live_blocks() == 0


def test_mid_flight_admission_interleaves_streams(params):
    """Three requests, two slots: the third is admitted the moment a slot
    frees, WHILE the other resident keeps decoding — its tokens appear
    between the survivor's tokens with nothing recompiled."""
    eng, events = _serve(CFG, params, temp=0.0, top_k=None,
                         max_new=[16, 4, 6], slots=2, num_blocks=33,
                         block_size=8, prefill_chunk=8)
    first2 = next(k for k, e in enumerate(events)
                  if e.rid == 2 and e.first)
    first_done = next(k for k, e in enumerate(events) if e.done)
    assert first2 > first_done  # admitted into a freed slot...
    # ...while an earlier request was still streaming
    assert any(e.rid != 2 for e in events[first2 + 1:])
    assert eng.sched.done == {0, 1, 2}


def test_scheduler_determinism_replays_identical_event_log(params):
    """Identical submitted trace -> identical event log, tick for tick,
    including through preemption (the tight pool from the eviction test).
    Everything downstream (bench numbers, battery rows) rests on this."""
    prompts = [np.array([3, 5, 7, 9, 11], np.int32),
               np.array([2, 4, 6, 8, 10, 12, 14], np.int32)]
    max_new = [40, 40]

    def once():
        eng, events = _serve(CFG, params, temp=0.7, top_k=12,
                             prompts=prompts, max_new=max_new, slots=2,
                             num_blocks=9, block_size=8, prefill_chunk=8)
        return ([(e.rid, e.token, e.first, e.done) for e in events],
                dict(eng.steps), eng.sched.preemptions)

    log1, steps1, pre1 = once()
    log2, steps2, pre2 = once()
    assert log1 == log2
    assert steps1 == steps2 and pre1 == pre2


# ---- intake validation ------------------------------------------------------


def test_submit_validation(params):
    # capacity 4 blocks = 32 positions (the trash block is never granted)
    eng = ServeEngine(CFG, params, slots=2, num_blocks=5, block_size=8,
                      prefill_chunk=8)
    with pytest.raises(ValueError, match="out of vocabulary"):
        eng.submit(Request(rid=0, prompt=np.array([99], np.int32),
                           max_new_tokens=4, rng=jax.random.PRNGKey(0)))
    with pytest.raises(ValueError, match="empty prompt"):
        eng.submit(Request(rid=1, prompt=np.array([], np.int32),
                           max_new_tokens=4, rng=jax.random.PRNGKey(0)))
    with pytest.raises(ValueError, match="exceeds"):
        eng.submit(Request(rid=2, prompt=np.array([1] * 60, np.int32),
                           max_new_tokens=8, rng=jax.random.PRNGKey(0)))
    with pytest.raises(ValueError, match="never fit"):
        # fits max_len (38 <= 64) but needs 5 blocks, capacity 4
        eng.submit(Request(rid=3, prompt=np.array([1] * 30, np.int32),
                           max_new_tokens=8, rng=jax.random.PRNGKey(0)))
    with pytest.raises(ValueError, match="must divide"):
        ServeEngine(CFG, params, slots=2, num_blocks=9, block_size=8,
                    prefill_chunk=7)


# ---- host-side block accounting ---------------------------------------------


def test_block_pool_accounting():
    pool = BlockPool(5, 8)
    assert pool.trash_block == 4 and pool.capacity == 4
    # lowest ids first, deterministically
    assert pool.alloc(1, 2) == [0, 1]
    # an unsatisfiable alloc changes nothing
    assert pool.alloc(2, 3) is None and pool.free_blocks == 2
    assert pool.alloc(2, 2) == [2, 3]  # trash block never handed out
    assert pool.live_blocks() == 4 and pool.owned_by(1) == [0, 1]
    pool.check_leaks()
    # ownership is enforced on free: no cross-request free, no double free
    with pytest.raises(ValueError, match="does not own"):
        pool.free(2, [0])
    pool.free(1, [0, 1])
    with pytest.raises(ValueError, match="does not own"):
        pool.free(1, [0, 1])
    assert pool.alloc(3, 1) == [0]  # freed blocks recycle lowest-first
    pool.check_leaks()
    # a leaked block is caught
    del pool._holders[0]
    with pytest.raises(AssertionError, match="leak"):
        pool.check_leaks()
    with pytest.raises(ValueError, match=">= 2 blocks"):
        BlockPool(1, 8)


def test_blocks_for_and_table_row():
    assert blocks_for(1, 8) == 1
    assert blocks_for(8, 8) == 1
    assert blocks_for(9, 8) == 2
    row = table_row([3, 1], 4, trash=9)
    np.testing.assert_array_equal(row, [3, 1, 9, 9])
    np.testing.assert_array_equal(table_row([], 3, trash=5), [5, 5, 5])


# ---- device-side gather / scatter -------------------------------------------


def test_gather_scatter_roundtrip_and_trash_isolation():
    """scatter_chunk through a table then gather_view back must equal the
    dense view, and a trash-pointing table row must leave every owned
    block untouched (the inactive-slot write path)."""
    r = np.random.RandomState(0)
    N, bs, H, hd = 5, 4, 2, 3  # legacy (B, S, H, hd) layout: seq_axis 1
    pool = jnp.asarray(r.randn(N, bs, H, hd), jnp.float32)
    tables = jnp.asarray([[2, 0, 3], [4, 4, 4]], jnp.int32)  # trash id 4
    view = gather_view(pool, tables, seq_axis=1)
    assert view.shape == (2, 3 * bs, H, hd)
    np.testing.assert_array_equal(
        np.asarray(view[0, :bs]), np.asarray(pool[2]))
    np.testing.assert_array_equal(
        np.asarray(view[1, bs:2 * bs]), np.asarray(pool[4]))
    # write a 4-token chunk for request 0 at logical position 2 (straddles
    # physical blocks 2 and 0) while request 1's row points at trash
    chunk = jnp.asarray(r.randn(2, 4, H, hd), jnp.float32)
    idx = jnp.asarray([2, 0], jnp.int32)
    out = scatter_chunk(pool, chunk, tables, idx, block_size=bs,
                        seq_axis=1)
    got = gather_view(out, tables, seq_axis=1)
    np.testing.assert_array_equal(np.asarray(got[0, 2:6]),
                                  np.asarray(chunk[0]))
    # request 0's untouched positions survive
    np.testing.assert_array_equal(np.asarray(got[0, :2]),
                                  np.asarray(view[0, :2]))
    np.testing.assert_array_equal(np.asarray(got[0, 6:]),
                                  np.asarray(view[0, 6:]))
    # request 1's trash-routed write left every unwritten block intact
    # (request 0 touched only physical blocks 2 and 0)
    np.testing.assert_array_equal(np.asarray(out[1]), np.asarray(pool[1]))
    np.testing.assert_array_equal(np.asarray(out[3]), np.asarray(pool[3]))


# ---- paged byte model -------------------------------------------------------


def test_paged_byte_model_charges_live_blocks_not_max_len():
    per_slot = CFG.num_heads * cache_slot_bytes(CFG.head_dim, CFG.dtype)
    got = paged_decode_cache_bytes_per_step(
        CFG, block_size=8, live_blocks=3, active_slots=2)
    assert got == CFG.num_layers * (3 * 8 + 2) * per_slot
    # strictly below the dense model's batch * max_len charge
    assert got < decode_cache_bytes_per_step(CFG, 2)
    # int8 pool: 1-byte slots + f32 scales through the shared definition
    i8 = paged_decode_cache_bytes_per_step(
        dataclasses.replace(CFG, kv_dtype="int8"), block_size=8,
        live_blocks=3, active_slots=2)
    assert i8 < got


# ---- program plumbing -------------------------------------------------------


def test_step_fns_donation_declared_and_gated():
    """The pool donation INTENT is always (1,) — the lint contract audits
    it in alias mode — but actual donation is gated off on the CPU test
    backend (no input-output aliasing there, same as make_generate_fn)."""
    fns = build_step_fns(CFG, slots=2, num_blocks=9, block_size=8,
                        prefill_chunk=8)
    assert fns.declared_donate_argnums == (1,)
    assert fns.donates_pool == (jax.default_backend() != "cpu")
    assert fns.cfg.paged_num_blocks == 9
    assert fns.n_blk == CFG.max_len // 8
    # memoized on everything that reaches the trace: a second engine at
    # the same geometry reuses the SAME jitted pair (slots / chunk width
    # shape-specialize inside jit and deliberately don't key the memo),
    # while a different pool geometry or sampling knob builds fresh
    assert build_step_fns(CFG, slots=4, num_blocks=9, block_size=8,
                          prefill_chunk=16) is fns
    assert build_step_fns(CFG, slots=2, num_blocks=17, block_size=8,
                          prefill_chunk=8) is not fns
    assert build_step_fns(CFG, slots=2, num_blocks=9, block_size=8,
                          prefill_chunk=8, temperature=0.5) is not fns


# ---- serving under fire (PR 11) ---------------------------------------------
# Request lifecycle (cancel / deadlines / shedding), chaos absorption, and
# engine snapshot/restore. Everything here reuses the geometries the tests
# above already compiled (the build_step_fns memo), so this whole section
# adds no new program compiles to tier-1.


def _submit_all(eng, *, prompts=PROMPTS, max_new=MAX_NEW):
    for i, (p, mn) in enumerate(zip(prompts, max_new)):
        eng.submit(Request(rid=i, prompt=p, max_new_tokens=mn,
                           rng=jax.random.PRNGKey(100 + i)))


def test_pick_victim_is_youngest_admission_deterministically(params):
    """The documented tie-break: the victim is the YOUNGEST resident by
    admission order (highest admitted_seq — unique per admission, so the
    max is total and replay can never diverge), excluding the growing
    slot and blockless residents."""
    sch = Scheduler(slots=3, num_blocks=9, block_size=8, prefill_chunk=8,
                    max_len=64)
    key = np.asarray(jax.random.PRNGKey(0))

    def mk(rid, seq, blocks):
        return _Slot(rid=rid, prompt=np.array([1], np.int32), budget=4,
                     rng=key, blocks=blocks, admitted_seq=seq)

    sch.slots = [mk(0, 5, [0]), mk(1, 9, [1]), mk(2, 7, [2])]
    assert sch._pick_victim(exclude=0) == 1  # seq 9 is youngest
    assert sch._pick_victim(exclude=1) == 2  # excluding it: seq 7
    sch.slots[1] = None
    assert sch._pick_victim(exclude=0) == 2
    sch.slots[2].blocks = []  # blockless: evicting frees nothing
    assert sch._pick_victim(exclude=0) is None

    # end to end: under forced eviction the victim SEQUENCE is a pure
    # function of the submitted trace — two runs preempt identical rids
    # in identical order
    prompts = [np.array([3, 5, 7, 9, 11], np.int32),
               np.array([2, 4, 6, 8, 10, 12, 14], np.int32)]

    def victims_once():
        eng = ServeEngine(CFG, params, slots=2, num_blocks=9,
                          block_size=8, prefill_chunk=8, temperature=0.7,
                          top_k=12)
        victims = []
        orig = eng.sched._preempt

        def spy(i):
            victims.append(eng.sched.slots[i].rid)
            return orig(i)

        eng.sched._preempt = spy
        for i, p in enumerate(prompts):
            eng.submit(Request(rid=i, prompt=p, max_new_tokens=40,
                               rng=jax.random.PRNGKey(100 + i)))
        eng.run()
        return victims

    v1, v2 = victims_once(), victims_once()
    assert v1 and v1 == v2


def test_cancel_frees_resources_and_preserves_prefix(params):
    """Client cancellation mid-decode: one terminal event at the next
    step boundary, slot+blocks freed (check_leaks clean), survivors
    bitwise, and the cancelled stream is a bitwise PREFIX of its
    uninterrupted one-shot run — cancellation never corrupts what was
    already delivered."""
    eng = ServeEngine(CFG, params, slots=2, num_blocks=33, block_size=8,
                      prefill_chunk=8, temperature=0.8, top_k=10)
    _submit_all(eng)
    events = []
    for _ in range(6):  # rid 0 is mid-decide: >=1 token, budget unspent
        evs, _ = eng.step()
        events.extend(evs)
    assert eng.cancel(0) is True
    assert eng.cancel(99) is False  # unknown rid: a no-op, not an error
    events.extend(eng.run())
    term = [e for e in events if e.rid == 0 and e.status == "cancelled"]
    assert len(term) == 1 and term[0].token == -1 and term[0].done
    assert eng.cancel(0) is False  # already terminal: a no-op
    got = eng.completions()
    for i in (1, 2):  # survivors: completely unaffected, bitwise
        assert got[i] == _oracle(CFG, params, i, 0.8, 10), f"req {i}"
    o0 = _oracle(CFG, params, 0, 0.8, 10)
    assert 0 < len(got[0]) < len(o0) and got[0] == o0[:len(got[0])]
    assert eng.sched.finished[0] == "cancelled"
    assert eng.health()["cancelled"] == 1
    eng.sched.pool.check_leaks()
    assert eng.live_blocks() == 0


def test_deadlines_expire_at_step_boundaries(params):
    """TTFT and total deadlines, measured from the ORIGINAL arrival and
    evaluated at step boundaries by the sweep. run()'s now=inf would
    expire every deadline instantly — deadlines need a clock-driving
    caller (docs/serving.md), so this test advances now explicitly."""
    eng = ServeEngine(CFG, params, slots=2, num_blocks=33, block_size=8,
                      prefill_chunk=8, temperature=0.0)
    p0, p1, p2 = PROMPTS
    eng.submit(Request(rid=0, prompt=p0, max_new_tokens=8,
                       rng=jax.random.PRNGKey(100)))
    # expires mid-decode: ~7 ticks of service at 0.01s/tick
    eng.submit(Request(rid=1, prompt=p1, max_new_tokens=6,
                       rng=jax.random.PRNGKey(101), deadline_s=0.075))
    # both slots are busy, so this one waits queued; TTFT 0 expires it
    # at the first swept boundary without it ever emitting
    eng.submit(Request(rid=2, prompt=p2, max_new_tokens=10,
                       rng=jax.random.PRNGKey(102), ttft_deadline_s=0.0))
    now, events, ticks = 0.0, [], 0
    while eng.sched.has_queued or eng.sched.has_resident:
        evs, kind = eng.step(now)
        events.extend(evs)
        now += 0.01
        ticks += 1
        assert ticks < 200
    statuses = {e.rid: e.status for e in events if e.token < 0}
    assert statuses == {1: "expired", 2: "expired"}
    got = eng.completions()
    assert got[0] == _oracle(CFG, params, 0, 0.0, None)  # no deadline set
    o1 = _oracle(CFG, params, 1, 0.0, None)
    assert 0 < len(got[1]) < len(o1) and got[1] == o1[:len(got[1])]
    assert got[2] == []  # expired while queued: zero tokens
    assert eng.health()["expired"] == 2
    eng.sched.pool.check_leaks()
    # the predicted-TTFT gate is warm now (finite clock above): a request
    # whose TTFT budget is already below recent TTFTs is shed at the door
    assert eng._ttft_ewma is not None and eng._ttft_ewma > 0
    with pytest.raises(EngineOverloaded, match="recent TTFT"):
        eng.submit(Request(rid=7, prompt=p0, max_new_tokens=4,
                           rng=jax.random.PRNGKey(7),
                           ttft_deadline_s=eng._ttft_ewma / 2))
    assert eng.health()["shed"] == 1


def test_overload_sheds_retriably_at_the_door(params):
    """Queue-depth admission control: past max_queue, submit raises the
    retriable EngineOverloaded and records NOTHING — the identical
    resubmission later yields the identical stream bitwise."""
    eng = ServeEngine(CFG, params, slots=2, num_blocks=33, block_size=8,
                      prefill_chunk=8, temperature=0.8, top_k=10,
                      max_queue=2)
    _submit_all(eng, prompts=PROMPTS[:2], max_new=MAX_NEW[:2])
    with pytest.raises(EngineOverloaded, match="retry"):
        eng.submit(Request(rid=2, prompt=PROMPTS[2],
                           max_new_tokens=MAX_NEW[2],
                           rng=jax.random.PRNGKey(102)))
    assert EngineOverloaded.retriable is True
    assert eng.sched.shed == 1 and 2 not in eng.sched.emitted
    eng.run()
    eng.submit(Request(rid=2, prompt=PROMPTS[2], max_new_tokens=MAX_NEW[2],
                       rng=jax.random.PRNGKey(102)))
    eng.run()
    assert eng.completions()[2] == _oracle(CFG, params, 2, 0.8, 10)
    assert eng.health()["shed"] == 1
    eng.sched.pool.check_leaks()


def test_step_exception_and_pool_pressure_storm_is_invisible(params):
    """An injected launch failure retries the SAME tick bitwise; a pool
    -pressure spike forces eviction/re-prefill. Neither may change a
    single emitted token, leak a block, or leave a fault unabsorbed."""
    sched = FaultSchedule([Fault("serve_step_exception", 2),
                           Fault("pool_pressure", 4, 4.0)])
    eng = ServeEngine(CFG, params, slots=2, num_blocks=33, block_size=8,
                      prefill_chunk=8, temperature=0.8, top_k=10,
                      chaos=sched, retry_base_delay_s=0.001)
    _submit_all(eng)
    eng.run()
    got = eng.completions()
    for i in range(len(PROMPTS)):
        assert got[i] == _oracle(CFG, params, i, 0.8, 10), f"req {i}"
    assert sched.serve_events() == [] and len(sched.fired) == 2
    eng.sched.pool.check_leaks()
    assert eng.live_blocks() == 0


def test_arrival_burst_and_client_abandon(params):
    """A burst-injected request streams to completion bitwise like any
    other; a client_abandon fault cancels a live rid whose delivered
    tokens stay a bitwise prefix. check_leaks clean throughout."""
    def burst(n, now):
        assert n == 1
        return [Request(rid=1000, prompt=PROMPTS[0], max_new_tokens=4,
                        rng=jax.random.PRNGKey(42), arrival=now)]

    sched = FaultSchedule([Fault("arrival_burst", 3, 1.0),
                           Fault("client_abandon", 6, 0.0)])
    eng = ServeEngine(CFG, params, slots=2, num_blocks=33, block_size=8,
                      prefill_chunk=8, temperature=0.8, top_k=10,
                      chaos=sched, burst_factory=burst)
    _submit_all(eng)
    eng.run()
    assert sched.serve_events() == [] and len(sched.fired) == 2
    # the burst request == its own one-shot run, bitwise
    gen = make_generate_fn(CFG, max_new_tokens=4, temperature=0.8,
                           top_k=10)
    out = gen(params, PROMPTS[0][None], jax.random.PRNGKey(42))
    assert eng.completions()[1000] == \
        np.asarray(out)[0, len(PROMPTS[0]):].tolist()
    # abandon index 0 cancelled the lowest live rid (= 0, still serving)
    cancelled = [r for r, st in eng.sched.finished.items()
                 if st == "cancelled"]
    assert cancelled == [0]
    got0 = eng.completions()[0]
    o0 = _oracle(CFG, params, 0, 0.8, 10)
    assert got0 == o0[:len(got0)]
    eng.sched.pool.check_leaks()
    assert eng.live_blocks() == 0


def test_watchdog_breaks_hung_step_and_retry_is_bitwise(params):
    """A hung compiled step becomes WatchdogTimeout (not a silent stall)
    and retries like any transient — the re-run tick is bitwise the
    original. deadline=1.5s: the per-attempt deadline must cover a
    first-launch XLA compile (~0.25s on CPU), the operational footgun
    docs/serving.md calls out."""
    eng = ServeEngine(CFG, params, slots=2, num_blocks=33, block_size=8,
                      prefill_chunk=8, temperature=0.0,
                      step_deadline_s=1.5, retry_base_delay_s=0.01)
    # copy the memoized namespace before wrapping — mutating the shared
    # one would poison every other engine at this geometry
    eng.fns = SimpleNamespace(**vars(eng.fns))
    real = eng.fns.decode
    state = {"hung": False}

    def hang_once(*a, **kw):
        if not state["hung"]:
            state["hung"] = True
            end = time.monotonic() + 30.0
            while time.monotonic() < end:  # interruptible: small slices
                time.sleep(0.02)
        return real(*a, **kw)

    eng.fns.decode = hang_once
    _submit_all(eng, prompts=PROMPTS[:2], max_new=MAX_NEW[:2])
    t0 = time.perf_counter()
    eng.run()
    assert time.perf_counter() - t0 < 15.0  # the 30s hang was broken
    assert state["hung"]
    got = eng.completions()
    for i in range(2):
        assert got[i] == _oracle(CFG, params, i, 0.0, None), f"req {i}"
    eng.sched.pool.check_leaks()
    eng.close()


def test_engine_kill_restore_resumes_bitwise(params, tmp_path):
    """The tentpole pin: snapshot, keep serving, kill, restore a FRESH
    engine from the snapshot — every in-flight stream continues and ends
    bitwise identical to an uninterrupted run, and the span the kill
    dropped is re-emitted bitwise (position-derived keys; the pool is
    never saved, residents re-prefill as continuations)."""
    kw = dict(slots=2, num_blocks=33, block_size=8, prefill_chunk=8,
              temperature=0.8, top_k=10,
              snapshot_dir=str(tmp_path / "snap"))
    eng = ServeEngine(CFG, params, **kw)
    _submit_all(eng)
    for _ in range(7):
        eng.step()
    label = eng.save_snapshot()
    assert label is not None
    for _ in range(3):  # post-snapshot progress the restore must re-earn
        eng.step()
    pre = eng.completions()
    assert any(pre.values())  # the kill really drops emitted tokens
    eng.close()  # the "kill": nothing after the snapshot persists

    eng2 = ServeEngine(CFG, params, **kw)
    assert eng2.restore_latest_snapshot() == label
    eng2.run()
    got = eng2.completions()
    for i in range(len(PROMPTS)):
        assert got[i] == _oracle(CFG, params, i, 0.8, 10), f"req {i}"
        # everything delivered pre-kill is a prefix of the final stream
        assert pre[i] == got[i][:len(pre[i])]
    eng2.sched.pool.check_leaks()
    assert eng2.live_blocks() == 0
    eng2.close()


def test_snapshot_ladder_skips_corrupt_through_eviction(params, tmp_path):
    """snapshot_corrupt damages the newest snapshot post-commit; restore
    must ladder down to the previous valid one and STILL land every
    stream bitwise — here through the forced-eviction geometry, so the
    restore path composes with preemption/continuation."""
    prompts = [np.array([3, 5, 7, 9, 11], np.int32),
               np.array([2, 4, 6, 8, 10, 12, 14], np.int32)]
    max_new = [40, 40]
    sched = FaultSchedule([Fault("snapshot_corrupt", 24)])
    kw = dict(slots=2, num_blocks=9, block_size=8, prefill_chunk=8,
              temperature=0.7, top_k=12, snapshot_dir=str(tmp_path / "s"))
    eng = ServeEngine(CFG, params, chaos=sched, **kw)
    for i, (p, mn) in enumerate(zip(prompts, max_new)):
        eng.submit(Request(rid=i, prompt=p, max_new_tokens=mn,
                           rng=jax.random.PRNGKey(100 + i)))
    for t in range(26):  # saves land at ticks 8, 16, 24; corrupt at 24
        eng.step()
        if (t + 1) % 8 == 0:
            eng.save_snapshot()
    assert sched.serve_events() == []  # the corruption really landed
    eng.close()

    eng2 = ServeEngine(CFG, params, **kw)
    assert eng2.restore_latest_snapshot() == 16  # 24 is damaged: fall back
    eng2.run()
    got = eng2.completions()
    for i in range(2):
        assert got[i] == _oracle(CFG, params, i, 0.7, 12, prompts=prompts,
                                 max_new=max_new), f"req {i}"
    assert eng.sched.preemptions + eng2.sched.preemptions >= 1
    eng2.sched.pool.check_leaks()
    eng2.close()


@pytest.mark.parametrize("kv,impl", [("int8", "dense"), (None, "pallas")])
def test_snapshot_restore_across_decode_levers(params, kv, impl, tmp_path):
    """Kill+restore composes with the decode levers: the restored
    engine's re-prefilled continuations stay bitwise under int8 KV and
    the paged Pallas read path too."""
    cfg = dataclasses.replace(CFG, kv_dtype=kv, decode_impl=impl)
    prompts, max_new = PROMPTS[:2], MAX_NEW[:2]
    kw = dict(slots=2, num_blocks=17, block_size=8, prefill_chunk=8,
              temperature=0.8, top_k=10, snapshot_dir=str(tmp_path / "s"))
    eng = ServeEngine(cfg, params, **kw)
    _submit_all(eng, prompts=prompts, max_new=max_new)
    for _ in range(5):
        eng.step()
    assert eng.save_snapshot() is not None
    eng.step()
    eng.close()
    eng2 = ServeEngine(cfg, params, **kw)
    assert eng2.restore_latest_snapshot() is not None
    eng2.run()
    for i in range(2):
        assert eng2.completions()[i] == _oracle(
            cfg, params, i, 0.8, 10, prompts=prompts, max_new=max_new), \
            f"req {i} kv={kv} impl={impl}"
    eng2.sched.pool.check_leaks()
    eng2.close()


# ---- prefix sharing, tenancy, multi-LoRA (PR 12) ----------------------------
# The engine tests here reuse the geometries compiled above (the
# build_step_fns memo) wherever possible; the only new compiles are the
# tiny LoRA config's step pair and its one-shot oracle.


def test_block_pool_refcount_share():
    """Refcounted sharing: a full block may be claimed by ref-bump, every
    holder frees independently, the block returns to the free list only
    at refcount zero, and live_blocks() counts DISTINCT blocks (the dedup
    closed form the byte model charges)."""
    pool = BlockPool(6, 8)
    assert pool.alloc(1, 3) == [0, 1, 2]
    pool.share(2, [0, 1])                 # rid 2 claims rid 1's prefix
    assert pool.refcount(0) == 2 and pool.refcount(2) == 1
    assert pool.owned_by(2) == [0, 1]
    # 3 + 2 claimed block-refs, but only 3 distinct live blocks
    assert pool.live_blocks() == 3 and pool.free_blocks == 2
    pool.check_leaks()
    with pytest.raises(ValueError, match="already holds"):
        pool.share(2, [0])                # no double-claim by one holder
    with pytest.raises(ValueError, match="dead block"):
        pool.share(3, [4])                # only live blocks are shareable
    pool.free(1, [0, 1, 2])               # rid 1 exits; rid 2's refs hold
    assert pool.live_blocks() == 2 and pool.refcount(0) == 1
    assert pool.alloc(5, 4) is None       # 0,1 are NOT free: only 2,3,4
    assert pool.alloc(5, 3) == [2, 3, 4]
    pool.free(2, [0, 1])                  # last holder: now they recycle
    assert pool.alloc(5, 2) == [0, 1]
    pool.free(5, [0, 1, 2, 3, 4])
    assert pool.live_blocks() == 0
    pool.check_leaks()


def test_prefix_index_match_insert_evict():
    """The radix trie over a real pool: block-granularity match, existing
    -node-wins insert, LRU leaf-first eviction that never touches a block
    a resident still holds, and adapter keying."""
    from distributed_tensorflow_guide_tpu.serve.prefix_index import (
        CACHE_RID,
        PrefixIndex,
    )

    pool = BlockPool(8, 4)
    idx = PrefixIndex(4)
    toks = list(range(10))                # 2 full blocks + a partial
    blocks = pool.alloc(0, 3)
    assert idx.insert(toks, blocks, pool=pool) == 2   # partial never cached
    assert idx.size == 2 and pool.refcount(blocks[0]) == 2
    assert idx.match(toks) == blocks[:2]
    assert idx.match(toks[:7]) == blocks[:1]          # 1 full block only
    assert idx.match([9, 9, 9, 9]) == []
    assert idx.match(toks, adapter=1) == []           # adapter-keyed root
    # existing node wins: a concurrent duplicate's blocks are not cached
    dup = pool.alloc(1, 2)
    assert idx.insert(toks[:8], dup, pool=pool) == 0
    assert idx.match(toks) == blocks[:2]
    pool.free(1, dup)
    # the request exits; the cache's refs keep both blocks live
    pool.free(0, blocks)
    assert pool.live_blocks() == 2
    # eviction is leaf-first: node 1 (deeper) goes before node 0 even
    # though node 0 is colder — an inner node is never evictable
    assert idx.evict_one(pool) == blocks[1]
    assert idx.match(toks) == blocks[:1]
    # a resident's ref pins the survivor: nothing evictable
    pool.share(7, [blocks[0]])
    assert idx.evict_one(pool) is None
    pool.free(7, [blocks[0]])
    assert idx.evict_one(pool) == blocks[0]
    assert idx.size == 0 and pool.live_blocks() == 0
    pool.check_leaks()
    # drop releases everything at once (engine close)
    b2 = pool.alloc(3, 2)
    idx.insert(list(range(8)), b2, pool=pool)
    pool.free(3, b2)
    assert idx.drop(pool) == 2
    pool.check_leaks()
    assert pool.refcount(0) == 0 and CACHE_RID < 0


def test_prefix_sharing_bitwise_and_dedup(params):
    """The tentpole pin: with the prefix cache on, a repeat prompt claims
    its cached blocks by ref-bump and prefills only the suffix — and the
    stream stays bitwise identical to the same request served ALONE with
    the cache off. A diverging suffix (COW fork) also stays bitwise: the
    shared blocks are read-only, private blocks take every write."""
    fork = np.array([1] * 16 + [2], np.int32)   # shares 2 blocks with
    prompts = [PROMPTS[2], fork]                # PROMPTS[2] = [1]*17
    kw = dict(slots=2, num_blocks=33, block_size=8, prefill_chunk=8,
              temperature=0.8, top_k=10)
    eng = ServeEngine(CFG, params, prefix_cache=True, **kw)
    eng.submit(Request(rid=0, prompt=PROMPTS[2], max_new_tokens=MAX_NEW[2],
                       rng=jax.random.PRNGKey(102)))
    eng.run()
    warm_prefills = eng.steps["prefill"]        # 17 tokens -> 3 chunks
    assert eng.health()["prefix_nodes"] == 2    # [1]*8 twice, cached
    # repeat + COW fork, served concurrently off the shared prefix
    eng.submit(Request(rid=1, prompt=PROMPTS[2], max_new_tokens=MAX_NEW[2],
                       rng=jax.random.PRNGKey(102)))
    eng.submit(Request(rid=2, prompt=fork, max_new_tokens=MAX_NEW[2],
                       rng=jax.random.PRNGKey(100)))
    eng.run()
    got = eng.completions()
    # both claimed 16 tokens; each prefilled exactly 1 suffix chunk
    assert eng.steps["prefill"] == warm_prefills + 2
    assert eng.health()["prefill_tokens_saved"] == 32
    assert eng.health()["prefix_hit_tokens"] == 32
    # bitwise: repeat == the cache-off oracle of the SAME request alone
    assert got[1] == got[0] == _oracle(CFG, params, 2, 0.8, 10)
    assert got[2] == _oracle(CFG, params, 0, 0.8, 10,
                             prompts=[fork], max_new=[MAX_NEW[2]])
    eng.close()                                 # drops the cache's refs
    eng.sched.pool.check_leaks()
    assert eng.live_blocks() == 0


@pytest.mark.parametrize("kv,impl", [("int8", "dense"), (None, "pallas")])
def test_prefix_sharing_parity_across_decode_levers(params, kv, impl):
    """Prefix claims compose with the decode levers: the repeat request
    reads its shared blocks through the int8/pallas read path (scale
    blocks ride the same block ids) and still reproduces the cache-off
    one-shot stream bitwise. Same geometry as
    test_engine_parity_across_decode_levers — no new compiles."""
    cfg = dataclasses.replace(CFG, kv_dtype=kv, decode_impl=impl)
    eng = ServeEngine(cfg, params, prefix_cache=True, slots=2,
                      num_blocks=17, block_size=8, prefill_chunk=8,
                      temperature=0.8, top_k=10)
    eng.submit(Request(rid=0, prompt=PROMPTS[1], max_new_tokens=MAX_NEW[1],
                       rng=jax.random.PRNGKey(101)))
    eng.run()
    assert eng.health()["prefix_nodes"] == 1    # one full block cached
    eng.submit(Request(rid=1, prompt=PROMPTS[1], max_new_tokens=MAX_NEW[1],
                       rng=jax.random.PRNGKey(101)))
    eng.run()
    assert eng.health()["prefill_tokens_saved"] == 8
    got = eng.completions()
    assert got[0] == got[1] == _oracle(cfg, params, 1, 0.8, 10), \
        f"kv={kv} impl={impl}"
    eng.close()
    eng.sched.pool.check_leaks()


def test_prefix_dedup_charges_shared_blocks_once(params):
    """live_blocks() closed form while shared prefixes are RESIDENT: two
    claimers of a 2-block prefix plus their private suffixes count the
    shared blocks once — the paged byte model's denominator."""
    kw = dict(slots=2, num_blocks=33, block_size=8, prefill_chunk=8,
              temperature=0.0, top_k=None)
    eng = ServeEngine(CFG, params, prefix_cache=True, **kw)
    eng.submit(Request(rid=0, prompt=PROMPTS[2], max_new_tokens=MAX_NEW[2],
                       rng=jax.random.PRNGKey(102)))
    eng.run()
    eng.submit(Request(rid=1, prompt=PROMPTS[2], max_new_tokens=MAX_NEW[2],
                       rng=jax.random.PRNGKey(102)))
    eng.submit(Request(rid=2, prompt=PROMPTS[2], max_new_tokens=MAX_NEW[2],
                       rng=jax.random.PRNGKey(102)))
    eng.step()  # both admitted: shared prefix claimed, suffixes private
    pool = eng.sched.pool
    # the prompt needs 3 blocks: 2 shared (also the cache's 2) + 1
    # private tail each => 4 distinct live blocks, not 6 — the shared
    # pair is charged once
    assert pool.owned_by(1)[:2] == pool.owned_by(2)[:2]
    assert pool.live_blocks() == 4
    assert sum(len(pool.owned_by(r)) for r in (1, 2)) == 6
    eng.run()
    assert eng.completions()[1] == eng.completions()[2] \
        == _oracle(CFG, params, 2, 0.0, None)
    eng.close()
    eng.sched.pool.check_leaks()


def test_prefix_eviction_and_preemption_parity(params):
    """The tight pool (nb=9) with the cache on: cached blocks are evicted
    LRU leaf-first to feed decode growth BEFORE any resident is
    preempted, and every stream still lands bitwise. Prompts span 2 full
    blocks each so finishing really populates the trie."""
    prompts = [np.array([1] * 17, np.int32),
               np.array([2] * 17, np.int32),
               np.array([3] * 17, np.int32)]
    max_new = [30, 30, 30]
    eng = ServeEngine(CFG, params, slots=2, num_blocks=9, block_size=8,
                      prefill_chunk=8, temperature=0.7, top_k=12,
                      prefix_cache=True)
    _submit_all(eng, prompts=prompts[:2], max_new=max_new[:2])
    eng.run()
    # the finished prompts (and their preempted continuations) now fill
    # the trie; a cold third prompt must evict cached leaves to fit
    assert eng.health()["prefix_nodes"] >= 4
    eng.submit(Request(rid=2, prompt=prompts[2], max_new_tokens=max_new[2],
                       rng=jax.random.PRNGKey(102)))
    eng.run()
    assert eng.sched.prefix_evictions >= 1  # the cache yielded to decode
    got = eng.completions()
    for i in range(3):
        assert got[i] == _oracle(CFG, params, i, 0.7, 12, prompts=prompts,
                                 max_new=max_new), f"req {i}"
    eng.close()
    eng.sched.pool.check_leaks()
    assert eng.live_blocks() == 0


def test_scheduler_drr_interleaves_and_quotas_skip(params):
    """Host-side fair share: with a small quantum, deficit round-robin
    interleaves a backlogged tenant with a light one instead of FIFO
    head-of-line; a quota-blocked tenant is SKIPPED (never blocks the
    others); with the default quantum admission IS legacy FIFO."""
    key = np.asarray(jax.random.PRNGKey(0))
    p = np.array([1, 2, 3, 4, 5], np.int32)

    def mk(rid, tenant):
        return Request(rid=rid, prompt=p, max_new_tokens=8, rng=key,
                       tenant=tenant)

    # cost = blocks_for(5+8) = 2; quantum 1 -> every admit costs 2 rounds
    sch = Scheduler(slots=4, num_blocks=33, block_size=8, prefill_chunk=8,
                    max_len=64, drr_quantum=1)
    for r in [mk(0, 0), mk(1, 0), mk(2, 0), mk(3, 1)]:
        sch.submit(r)
    sch.admit(0.0)
    order = [s.rid for s in sorted(
        (s for s in sch.slots if s is not None),
        key=lambda s: s.admitted_seq)]
    assert order == [0, 3, 1, 2]  # tenant 1 jumps the tenant-0 backlog
    assert sch.tenants[0]["admitted"] == 3 and sch.tenants[1]["admitted"] == 1
    # a single tenant reduces to exact head-of-line FIFO (the PR-10/11
    # determinism pins above run through this same path unchanged)
    sch2 = Scheduler(slots=4, num_blocks=33, block_size=8, prefill_chunk=8,
                     max_len=64)
    for r in [mk(0, 0), mk(1, 0), mk(2, 0)]:
        sch2.submit(r)
    sch2.admit(0.0)
    order2 = [s.rid for s in sorted(
        (s for s in sch2.slots if s is not None),
        key=lambda s: s.admitted_seq)]
    assert order2 == [0, 1, 2]
    # a slots quota caps tenant 0 at 1 resident and SKIPS its backlog
    sch3 = Scheduler(slots=2, num_blocks=33, block_size=8, prefill_chunk=8,
                     max_len=64, tenant_quotas={0: {"slots": 1}})
    for r in [mk(0, 0), mk(1, 0), mk(2, 1)]:
        sch3.submit(r)
    sch3.admit(0.0)
    resident = {s.rid: s.tenant for s in sch3.slots if s is not None}
    assert resident == {0: 0, 2: 1}       # rid 1 waits; rid 2 not blocked
    assert [r.rid for r in sch3.queue] == [1]
    # a blocks quota below a request's worst-case footprint can NEVER be
    # satisfied — that is a caller error, rejected loudly at submit
    sch4 = Scheduler(slots=4, num_blocks=33, block_size=8, prefill_chunk=8,
                     max_len=64, tenant_quotas={0: {"blocks": 1}})
    with pytest.raises(ValueError, match="never fit"):
        sch4.submit(mk(9, 0))             # needs 2 blocks, quota caps at 1


def test_fair_share_absorbs_tenant_burst(params):
    """A chaos arrival_burst aimed at one tenant, with that tenant under
    a slots quota: the victim tenant's streams are untouched bitwise and
    the per-tenant health counters account for every burst request."""
    def burst(n, now, tenant):
        assert tenant == 0
        return [Request(rid=1000 + k, prompt=PROMPTS[0], max_new_tokens=4,
                        rng=jax.random.PRNGKey(42), arrival=now,
                        tenant=tenant) for k in range(n)]

    sched = FaultSchedule([Fault("arrival_burst", 3, 2.0, tenant=0)])
    eng = ServeEngine(CFG, params, slots=2, num_blocks=33, block_size=8,
                      prefill_chunk=8, temperature=0.8, top_k=10,
                      chaos=sched, burst_factory=burst,
                      tenant_quotas={0: {"slots": 1}})
    for i, (p, mn) in enumerate(zip(PROMPTS, MAX_NEW)):
        eng.submit(Request(rid=i, prompt=p, max_new_tokens=mn,
                           rng=jax.random.PRNGKey(100 + i), tenant=1))
    eng.run()
    assert sched.serve_events() == []
    got = eng.completions()
    for i in range(len(PROMPTS)):  # tenant 1: bitwise despite the burst
        assert got[i] == _oracle(CFG, params, i, 0.8, 10), f"req {i}"
    gen = make_generate_fn(CFG, max_new_tokens=4, temperature=0.8,
                           top_k=10)
    out = np.asarray(gen(params, PROMPTS[0][None],
                         jax.random.PRNGKey(42)))[0, len(PROMPTS[0]):]
    for rid in (1000, 1001):  # burst requests also land bitwise
        assert got[rid] == out.tolist()
    t = eng.health()["tenants"]
    assert t[0]["submitted"] == 2 and t[0]["done"] == 2
    assert t[1]["submitted"] == 3 and t[1]["done"] == 3
    eng.close()
    eng.sched.pool.check_leaks()


def test_multi_lora_batched_decode_bitwise(params):
    """Batched multi-LoRA: one shared decode step serves slots on
    different adapters via gathered low-rank deltas. Adapter 0 (the zero
    rows) is bitwise the BASE model; adapter k is bitwise the one-shot
    generate with that adapter's delta applied."""
    from distributed_tensorflow_guide_tpu.serve.engine import (
        init_adapter_bank,
    )

    cfg_l = dataclasses.replace(CFG, lora_rank=2, lora_adapters=2)
    bank = init_adapter_bank(cfg_l)
    keys = jax.random.split(jax.random.PRNGKey(7), len(jax.tree.leaves(bank)))
    bank = jax.tree.unflatten(
        jax.tree.structure(bank),
        [0.05 * jax.random.normal(k, l.shape, l.dtype).at[0].set(0.0)
         for k, l in zip(keys, jax.tree.leaves(bank))])
    eng = ServeEngine(cfg_l, params, slots=2, num_blocks=33, block_size=8,
                      prefill_chunk=8, temperature=0.8, top_k=10,
                      adapters=bank)
    eng.submit(Request(rid=0, prompt=PROMPTS[0], max_new_tokens=MAX_NEW[0],
                       rng=jax.random.PRNGKey(100), adapter=0))
    eng.submit(Request(rid=1, prompt=PROMPTS[1], max_new_tokens=MAX_NEW[1],
                       rng=jax.random.PRNGKey(101), adapter=1))
    eng.run()
    got = eng.completions()
    # adapter 0 == the base oracle, bitwise, even batched WITH adapter 1
    assert got[0] == _oracle(CFG, params, 0, 0.8, 10)
    gen1 = make_generate_fn(cfg_l, max_new_tokens=MAX_NEW[1],
                            temperature=0.8, top_k=10, adapters=bank,
                            adapter_id=1)
    o1 = np.asarray(gen1(params, PROMPTS[1][None],
                         jax.random.PRNGKey(101)))[0,
                                                   len(PROMPTS[1]):].tolist()
    assert got[1] == o1 and o1 != _oracle(CFG, params, 1, 0.8, 10)
    eng.close()
    eng.sched.pool.check_leaks()


def test_snapshot_restore_rebuilds_prefix_cache(params, tmp_path):
    """Kill+restore with sharing live: the trie is deliberately NOT in
    the snapshot — the restored engine's continuation re-prefills rebuild
    it deterministically, streams stay bitwise, and a post-restore repeat
    prompt hits the rebuilt cache."""
    kw = dict(slots=2, num_blocks=33, block_size=8, prefill_chunk=8,
              temperature=0.8, top_k=10, prefix_cache=True,
              snapshot_dir=str(tmp_path / "snap"))
    eng = ServeEngine(CFG, params, **kw)
    _submit_all(eng)
    for _ in range(7):
        eng.step()
    label = eng.save_snapshot()
    assert label is not None
    for _ in range(3):
        eng.step()
    eng.close()  # the kill: cache refs dropped, post-snapshot work lost

    eng2 = ServeEngine(CFG, params, **kw)
    assert eng2.restore_latest_snapshot() == label
    eng2.run()
    got = eng2.completions()
    for i in range(len(PROMPTS)):
        assert got[i] == _oracle(CFG, params, i, 0.8, 10), f"req {i}"
    # the rebuilt trie serves a repeat of the longest prompt from cache
    assert eng2.health()["prefix_nodes"] >= 2
    eng2.submit(Request(rid=9, prompt=PROMPTS[2],
                        max_new_tokens=MAX_NEW[2],
                        rng=jax.random.PRNGKey(102)))
    eng2.run()
    assert eng2.completions()[9] == _oracle(CFG, params, 2, 0.8, 10)
    # exactly the repeat's 16-token claim: the three distinct prompts
    # share no full block, so the restore continuations themselves save
    # nothing — a drift here means the claim path double-counted
    assert eng2.health()["prefill_tokens_saved"] == 16
    eng2.close()
    eng2.sched.pool.check_leaks()
    assert eng2.live_blocks() == 0


def test_tenant_adapter_submit_validation(params):
    eng = ServeEngine(CFG, params, slots=2, num_blocks=33, block_size=8,
                      prefill_chunk=8)
    with pytest.raises(ValueError, match="no lora_rank"):
        eng.submit(Request(rid=0, prompt=PROMPTS[0], max_new_tokens=4,
                           rng=jax.random.PRNGKey(0), adapter=1))
    with pytest.raises(ValueError, match="tenant"):
        eng.submit(Request(rid=1, prompt=PROMPTS[0], max_new_tokens=4,
                           rng=jax.random.PRNGKey(0), tenant=-1))
    with pytest.raises(ValueError, match="adapters"):
        ServeEngine(CFG, params, slots=2, num_blocks=33, block_size=8,
                    prefill_chunk=8, adapters={"x": jnp.zeros((1,))})
    with pytest.raises(ValueError, match="drr_quantum"):
        Scheduler(slots=2, num_blocks=9, block_size=8, prefill_chunk=8,
                  max_len=64, drr_quantum=0)


# ---- KV cache hierarchy: host-RAM spill tier (PR 16) ------------------------
# Demotion is the non-destructive rung under eviction: preempted residents
# and cold trie prefixes swap OUT to a host BlockStore and swap back IN at
# re-admission/claim time, so the streams below must equal the uninterrupted
# oracles BITWISE — the hierarchy buys goodput, never correctness. Every
# geometry here reuses step programs the tests above already compiled.


def test_block_store_holder_ledger():
    """The host tier mirrors the pool's refcounted discipline exactly:
    put=1 holder, share ref-bumps (double-hold raises), free deletes the
    payload only at refcount 0, a full store returns None with NO state
    change, and ids are never recycled."""
    store = BlockStore(capacity=2)
    row = [np.arange(4, dtype=np.float32)]
    h0 = store.put(1, row)
    h1 = store.put(1, [np.zeros((2,), np.int8)])
    assert store.put(1, row) is None            # full: rejected, no hold
    assert store.live_blocks() == 2
    store.share(2, [h0])
    assert store.refcount(h0) == 2
    with pytest.raises(ValueError, match="already holds"):
        store.share(2, [h0])
    with pytest.raises(ValueError, match="dead host block"):
        store.share(3, [99])
    store.free(1, [h0])                         # payload survives holder 2
    np.testing.assert_array_equal(store.get(h0)[0], row[0])
    with pytest.raises(ValueError, match="does not own"):
        store.free(3, [h1])
    store.free(2, [h0])
    with pytest.raises(ValueError, match="dead host block"):
        store.get(h0)
    assert store.owned_by(1) == [h1]
    assert store.bytes_stored() == 2
    assert store.stats() == {"live": 1, "shared": 0, "holds": 1,
                             "bytes": 2}
    h2 = store.put(2, row)                      # capacity freed back up
    assert h2 is not None and h2 > h1           # monotonic, not recycled
    store.check_leaks()


def test_spill_preemption_resumes_without_reprefill(params):
    """The eviction-parity pool squeeze, hierarchy ON: preemption demotes
    the victim's blocks to the host tier and re-admission swaps them back
    in instead of re-prefilling — same streams bitwise, strictly fewer
    prefill steps than the destructive run, both tiers leak-free. Also
    the zero-new-programs pin: the swap path is host-side by design, so
    an actively-spilling engine adds NOTHING to ``_STEP_FNS`` and shares
    the pool-only engine's memoized program pair outright."""
    from distributed_tensorflow_guide_tpu.serve.engine import _STEP_FNS
    prompts = [np.array([3, 5, 7, 9, 11], np.int32),
               np.array([2, 4, 6, 8, 10, 12, 14], np.int32)]
    max_new = [40, 40]
    base, _ = _serve(CFG, params, temp=0.7, top_k=12, prompts=prompts,
                     max_new=max_new, slots=2, num_blocks=9,
                     block_size=8, prefill_chunk=8)
    n0 = len(_STEP_FNS)
    eng, _ = _serve(CFG, params, temp=0.7, top_k=12, prompts=prompts,
                    max_new=max_new, slots=2, num_blocks=9,
                    block_size=8, prefill_chunk=8, host_blocks=16)
    sd = eng.sched
    assert sd.preemptions >= 1
    assert sd.spill_resumes >= 1                # demote->swap-in, not kill
    assert sd.spill_out_blocks > 0 and sd.spill_in_blocks > 0
    assert sd.swapin_tokens_saved > 0
    got = eng.completions()
    for i in range(2):
        assert got[i] == base.completions()[i] == _oracle(
            CFG, params, i, 0.7, 12, prompts=prompts, max_new=max_new), \
            f"req {i} diverged across demotion"
    assert eng.steps["prefill"] < base.steps["prefill"]
    assert len(_STEP_FNS) == n0                 # zero new step programs
    assert eng.fns is base.fns                  # the same memoized pair
    sd.check_leaks()                            # device + host, jointly
    assert eng.live_blocks() == 0
    assert eng.store.live_blocks() == 0         # all resumes drained


@pytest.mark.parametrize("kv,impl", [("int8", "dense"), (None, "pallas"),
                                     ("int8", "pallas")])
def test_spill_roundtrip_parity_across_levers(params, kv, impl):
    """Swap-out/swap-in is bitwise for every KV layout the pool can hold
    (f32 rows; int8 rows + f32 scale leaves; pallas decode): cache a
    prompt, demote its trie prefix to the host tier, then re-serve the
    same prompt — the claim promotes by h2d swap-in and the stream still
    equals the uninterrupted oracle."""
    cfg = dataclasses.replace(CFG, kv_dtype=kv, decode_impl=impl)
    prompts, max_new = PROMPTS[:2], MAX_NEW[:2]
    eng, _ = _serve(cfg, params, temp=0.8, top_k=10, prompts=prompts,
                    max_new=max_new, slots=2, num_blocks=17,
                    block_size=8, prefill_chunk=8, prefix_cache=True,
                    host_blocks=8)
    sd = eng.sched
    freed = sd.prefix.demote_many(sd.pool, sd._cache_demote_batch)
    assert freed                                # prompt 1 cached a block
    before = sd.spill_in_blocks
    eng.submit(Request(rid=9, prompt=prompts[1], max_new_tokens=max_new[1],
                       rng=jax.random.PRNGKey(101)))
    eng.run()
    assert sd.spill_in_blocks > before          # promoted by swap-in
    assert eng.completions()[9] == _oracle(
        cfg, params, 1, 0.8, 10, prompts=prompts, max_new=max_new), \
        f"spilled round-trip diverged kv={kv} impl={impl}"
    eng.close()
    sd.check_leaks()


def test_cow_shared_block_spills_once(params):
    """A device block with multiple holders crosses the tier boundary
    ONCE: the first demotion d2h-copies, the second ref-bumps the same
    host payload — pinned by exact byte accounting (one block's worth of
    d2h traffic for two demotions)."""
    eng = ServeEngine(CFG, params, temperature=0.0, top_k=None, slots=2,
                      num_blocks=33, block_size=8, prefill_chunk=8,
                      host_blocks=8)
    sd = eng.sched
    (b,) = sd.pool.alloc(7, 1)
    sd.pool.share(8, [b])                       # COW: two device holders
    h7 = sd._demote_block(7, b)
    once = sd.spill_d2h_bytes
    assert once == eng.store.bytes_stored() == spill_bytes_per_swap(
        CFG.num_layers, CFG.num_heads, 8, CFG.d_model // CFG.num_heads,
        None, activation_dtype_bytes=np.dtype(CFG.dtype).itemsize)
    h8 = sd._demote_block(8, b)
    assert h8 == h7                             # deduped onto one payload
    assert eng.store.refcount(h7) == 2
    assert sd.spill_out_blocks == 2             # both demotions counted...
    assert sd.spill_d2h_bytes == once           # ...but the bytes moved once
    sd.pool.free(7, [b])
    sd.pool.free(8, [b])
    eng.store.free(7, [h7])
    eng.store.free(8, [h8])
    sd.check_leaks()


@pytest.mark.parametrize("kv", [None, "int8"], ids=["f32", "int8"])
def test_spill_byte_model_is_exact(params, kv):
    """``spill_bytes_per_swap`` is EXACT, not a bound: one demoted
    block's host bytes equal the closed form for both KV layouts —
    activation-dtype K/V rows, plus the f32 scale leaves when
    quantized."""
    cfg = dataclasses.replace(CFG, kv_dtype=kv)
    eng = ServeEngine(cfg, params, temperature=0.8, top_k=10, slots=2,
                      num_blocks=33 if kv is None else 17, block_size=8,
                      prefill_chunk=8, host_blocks=4)
    sd = eng.sched
    (b,) = sd.pool.alloc(5, 1)
    h = sd._demote_block(5, b)
    model = spill_bytes_per_swap(
        CFG.num_layers, CFG.num_heads, 8, CFG.d_model // CFG.num_heads,
        kv, activation_dtype_bytes=np.dtype(CFG.dtype).itemsize)
    assert sd.spill_d2h_bytes == eng.store.bytes_stored() == model
    sd.pool.free(5, [b])
    eng.store.free(5, [h])
    sd.check_leaks()


def test_spilled_prefix_claim_promotes_by_swap_in(params):
    """The trie indexes prefixes BEYOND device residency: demote every
    cached prefix wholesale (trie keeps its structure, zero device
    blocks), then repeat the longest prompt — the claim swaps its two
    blocks back in, charges them to ``swapin_tokens_saved``, and the
    stream stays bitwise."""
    eng = ServeEngine(CFG, params, temperature=0.8, top_k=10, slots=2,
                      num_blocks=33, block_size=8, prefill_chunk=8,
                      prefix_cache=True, host_blocks=8)
    _submit_all(eng)
    eng.run()
    sd = eng.sched
    nodes = sd.prefix.size
    freed = sd.prefix.demote_many(sd.pool, sd._cache_demote_batch)
    assert len(freed) == nodes >= 3             # whole trie went host-side
    assert sd.prefix.stats()["spilled"] == nodes
    saved0 = sd.prefill_tokens_saved
    eng.submit(Request(rid=9, prompt=PROMPTS[2], max_new_tokens=MAX_NEW[2],
                       rng=jax.random.PRNGKey(102)))
    eng.run()
    assert eng.completions()[9] == _oracle(CFG, params, 2, 0.8, 10)
    assert sd.spill_in_blocks == 2              # the 16-token claim cap
    assert sd.swapin_tokens_saved == 16
    assert sd.prefill_tokens_saved - saved0 == 16
    eng.close()
    sd.check_leaks()


def test_warm_restart_reprefills_zero_cached_prefix_tokens(params,
                                                           tmp_path):
    """Kill + warm restore: with ``--persist-cache`` the snapshot carries
    the cache CONTENTS — the fresh engine's trie comes back entirely in
    the host tier (zero device blocks held), and a repeat prompt prefills
    ONLY its uncached suffix chunk: zero cached-prefix tokens are ever
    re-prefilled."""
    kw = dict(slots=2, num_blocks=33, block_size=8, prefill_chunk=8,
              temperature=0.8, top_k=10, prefix_cache=True,
              host_blocks=8, persist_cache=True,
              snapshot_dir=str(tmp_path / "snap"))
    eng = ServeEngine(CFG, params, **kw)
    _submit_all(eng)
    eng.run()
    nodes = eng.sched.prefix.size
    assert nodes >= 3
    assert eng.save_snapshot() is not None
    eng.close()                                 # the kill

    eng2 = ServeEngine(CFG, params, **kw)
    assert eng2.restore_latest_snapshot() is not None
    sd = eng2.sched
    assert sd.prefix.size == nodes              # the trie came back...
    assert sd.prefix.stats()["spilled"] == nodes
    assert sd.pool.live_blocks() == 0           # ...entirely host-side
    assert eng2.store.live_blocks() == nodes
    spill_in0 = sd.spill_in_blocks              # counters restore too —
    saved0 = sd.prefill_tokens_saved            # pin the DELTAS below
    pre0 = eng2.steps["prefill"]
    eng2.submit(Request(rid=9, prompt=PROMPTS[2],
                        max_new_tokens=MAX_NEW[2],
                        rng=jax.random.PRNGKey(102)))
    eng2.run()
    assert eng2.completions()[9] == _oracle(CFG, params, 2, 0.8, 10)
    # 17-token prompt, 16 cached: exactly ONE suffix-chunk prefill step
    assert eng2.steps["prefill"] - pre0 == 1
    assert sd.prefill_tokens_saved - saved0 == 16
    assert sd.spill_in_blocks - spill_in0 == 2
    eng2.close()
    sd.check_leaks()


def test_corrupt_cache_file_falls_back_to_cold(params, tmp_path):
    """The warm-cache file is best-effort, never load-bearing: a
    truncated payload, a flipped byte (CRC mismatch), or a missing
    sidecar each restore COLD — the snapshot restore itself still
    succeeds, the repeat prompt simply re-prefills, and the stream is
    still bitwise. Never a wrong token. (One shared warm run feeds all
    three corruption rungs — pristine file copies restored per rung.)"""
    import os
    import shutil
    kw = dict(slots=2, num_blocks=33, block_size=8, prefill_chunk=8,
              temperature=0.8, top_k=10, prefix_cache=True,
              host_blocks=8, persist_cache=True,
              snapshot_dir=str(tmp_path / "snap"))
    eng = ServeEngine(CFG, params, **kw)
    _submit_all(eng)
    eng.run()
    label = eng.save_snapshot()
    path = eng._cache_file(label)
    crc = path[:-4] + ".crc"
    eng.close()
    pristine = {p: open(p, "rb").read() for p in (path, crc)}

    for corruption in ("truncate", "bitflip", "no_crc"):
        for p, raw in pristine.items():
            with open(p, "wb") as f:
                f.write(raw)
        if corruption == "truncate":
            with open(path, "wb") as f:
                f.write(pristine[path][:len(pristine[path]) // 2])
        elif corruption == "bitflip":
            flipped = bytearray(pristine[path])
            flipped[len(flipped) // 2] ^= 0xFF
            with open(path, "wb") as f:
                f.write(bytes(flipped))
        else:
            os.remove(crc)

        eng2 = ServeEngine(CFG, params, **kw)
        assert eng2.restore_latest_snapshot() == label  # snapshot fine
        sd = eng2.sched
        assert sd.prefix.size == 0, corruption  # cache went cold, safely
        assert eng2.store.live_blocks() == 0
        pre0 = eng2.steps["prefill"]            # steps restore with the
        eng2.submit(Request(rid=9, prompt=PROMPTS[2],   # snapshot: deltas
                            max_new_tokens=MAX_NEW[2],
                            rng=jax.random.PRNGKey(102)))
        eng2.run()
        assert eng2.completions()[9] == _oracle(CFG, params, 2, 0.8, 10)
        assert eng2.steps["prefill"] - pre0 == 3, corruption  # full cold
        assert sd.spill_in_blocks == 0
        eng2.close()
        sd.check_leaks()
    shutil.rmtree(str(tmp_path / "snap"))


def test_spill_knob_validation(params):
    with pytest.raises(ValueError, match="host_blocks"):
        ServeEngine(CFG, params, slots=2, num_blocks=33, block_size=8,
                    prefill_chunk=8, host_blocks=-1)
    with pytest.raises(ValueError, match="persist_cache"):
        ServeEngine(CFG, params, slots=2, num_blocks=33, block_size=8,
                    prefill_chunk=8, persist_cache=True)


# ---- kill mid-snapshot, across real process boundaries (out of tier-1) ------


def _target_serve_kill_mid_snapshot(snap_dir, phase):
    """Subprocess target: phase "serve" snapshots durably, races an async
    snapshot against the parent's SIGKILL; phase "restore" restores the
    newest VALID snapshot in a fresh process and drains."""
    import pathlib
    import time as _time

    import jax
    import jax.numpy as jnp
    import numpy as np

    from distributed_tensorflow_guide_tpu.models.transformer import (
        Transformer,
        TransformerConfig,
    )
    from distributed_tensorflow_guide_tpu.serve.engine import (
        Request,
        ServeEngine,
    )

    cfg = TransformerConfig(vocab_size=64, num_layers=2, num_heads=2,
                            d_model=16, d_ff=32, max_len=64, causal=True,
                            dtype=jnp.float32)
    params = Transformer(cfg).init(
        jax.random.PRNGKey(0), jnp.zeros((2, 8), jnp.int32))["params"]
    eng = ServeEngine(cfg, params, slots=2, num_blocks=33, block_size=8,
                      prefill_chunk=8, temperature=0.8, top_k=10,
                      snapshot_dir=snap_dir)
    if phase == "serve":
        prompts = [np.array([3, 5, 7, 9, 11], np.int32),
                   np.array([2, 4, 6, 8, 10, 12, 14, 16, 18], np.int32)]
        for i, p in enumerate(prompts):
            eng.submit(Request(rid=i, prompt=p, max_new_tokens=8,
                               rng=jax.random.PRNGKey(100 + i)))
        for _ in range(5):
            eng.step()
        eng.save_snapshot()  # the durable baseline
        for _ in range(3):
            eng.step()
        eng.save_snapshot(async_=True)  # the kill races this commit
        pathlib.Path(snap_dir, "saved_marker").touch()
        _time.sleep(600)  # hold still; the parent kills us here
    label = eng.restore_latest_snapshot()
    eng.run()
    eng.close()
    return {"label": label,
            "completions": {int(k): list(v)
                            for k, v in eng.completions().items()}}


@pytest.mark.chaos
@pytest.mark.slow
def test_kill_mid_snapshot_then_restore_bitwise(tmp_path, params):
    """Run 1 is SIGKILLed while an async snapshot may still be mid-write
    — a real engine crash. Run 2 (a fresh process) must restore the
    newest snapshot that VERIFIES (the torn one is skipped by the
    manifest ladder) and finish every stream bitwise."""
    import pathlib

    from distributed_tensorflow_guide_tpu.runtime.multiprocess import (
        MultiProcessRunner,
        run_multiprocess,
    )

    d = str(tmp_path / "snap")
    runner = MultiProcessRunner(
        _target_serve_kill_mid_snapshot, 1, args=(d, "serve"), timeout=120,
    ).start()
    marker = pathlib.Path(d) / "saved_marker"
    deadline = time.time() + 90
    while time.time() < deadline and not marker.exists():
        time.sleep(0.02)
    assert marker.exists(), "run 1 never reached its snapshot point"
    runner.kill(0)  # SIGKILL: no barriers, no atexit — a real engine crash
    results = runner.join(raise_on_error=False)
    assert not results[0].ok

    results = run_multiprocess(_target_serve_kill_mid_snapshot, 1,
                               args=(d, "restore"), timeout=120)
    r = results[0].result
    assert r["label"] is not None  # SOME durable snapshot verified
    prompts = [np.array([3, 5, 7, 9, 11], np.int32),
               np.array([2, 4, 6, 8, 10, 12, 14, 16, 18], np.int32)]
    for i in (0, 1):  # JSON round-trip: rid keys come back as strings
        assert r["completions"][str(i)] == _oracle(
            CFG, params, i, 0.8, 10, prompts=prompts, max_new=[8, 8]), \
            f"req {i} diverged across the kill"

# ---- expert-parallel MoE decode (PR 19) -------------------------------------

MOE_CFG = dataclasses.replace(CFG, moe_experts=4, moe_capacity=2)


@pytest.fixture(scope="module")
def moe_params():
    return Transformer(MOE_CFG).init(
        jax.random.PRNGKey(0), jnp.zeros((2, 8), jnp.int32))["params"]


@pytest.mark.parametrize("temp,top_k", [(0.0, None), (0.8, 10)],
                         ids=["greedy", "sampled"])
def test_moe_engine_matches_one_shot_bitwise(moe_params, temp, top_k):
    """The MoE acceptance pin: router dispatch + capacity-bounded expert
    contraction run INSIDE the fixed-slot serve programs, and every
    completed stream still equals the request's solo one-shot run
    exactly. The oracle decodes one token at a time (t=1 <= capacity,
    so it can never overflow); the engine batches slots and may stall —
    parity holding anyway is what degrade-to-overflow promises: a hot
    expert costs TIME, never tokens."""
    eng, _ = _serve(MOE_CFG, moe_params, temp=temp, top_k=top_k, slots=2,
                    num_blocks=33, block_size=8, prefill_chunk=8)
    got = eng.completions()
    for i in range(len(PROMPTS)):
        assert got[i] == _oracle(MOE_CFG, moe_params, i, temp, top_k), \
            f"req {i}"
    assert eng.sched.done == {0, 1, 2}
    eng.sched.pool.check_leaks()
    assert eng.live_blocks() == 0


def test_moe_parity_through_eviction(moe_params):
    """The forced-eviction geometry under the MoE model: preemption,
    continuation re-prefill and capacity stalls compose, and every
    stream still lands bitwise on its one-shot oracle."""
    prompts = [np.array([3, 5, 7, 9, 11], np.int32),
               np.array([2, 4, 6, 8, 10, 12, 14], np.int32)]
    max_new = [40, 40]
    eng, _ = _serve(MOE_CFG, moe_params, temp=0.7, top_k=12,
                    prompts=prompts, max_new=max_new, slots=2,
                    num_blocks=9, block_size=8, prefill_chunk=8)
    assert eng.sched.preemptions >= 1
    got = eng.completions()
    for i in range(2):
        assert got[i] == _oracle(MOE_CFG, moe_params, i, 0.7, 12,
                                 prompts=prompts, max_new=max_new), \
            f"req {i} diverged across eviction"
    eng.sched.pool.check_leaks()


def test_moe_wq8_expert_banks_parity(moe_params):
    """Weight-only int8 expert banks: quantize_params folds the (E, d,
    ff) bank kernels to per-expert qkernel+scale, the engine decodes
    through wq_bank_matmul, and streams still match the one-shot oracle
    running the SAME quantized model bitwise — quantization changes the
    model, never the serving discipline."""
    from distributed_tensorflow_guide_tpu.ops import quant

    wq_cfg = dataclasses.replace(MOE_CFG, weight_dtype="int8")
    wq_params = quant.quantize_params(moe_params, bits=8)
    eng, _ = _serve(wq_cfg, wq_params, temp=0.8, top_k=10, slots=2,
                    num_blocks=33, block_size=8, prefill_chunk=8)
    got = eng.completions()
    for i in range(len(PROMPTS)):
        assert got[i] == _oracle(wq_cfg, wq_params, i, 0.8, 10), f"req {i}"
    eng.sched.pool.check_leaks()
    # the routed banks really are stored int8 (f32 router exempt)
    mlp = wq_params["block_0"]["mlp"]
    assert mlp["w_in"]["qkernel"].dtype == jnp.int8
    assert mlp["w_out"]["qkernel"].dtype == jnp.int8
    router_k = mlp["router"]["kernel"]
    assert getattr(router_k, "value", router_k).dtype == jnp.float32


def test_moe_capacity_degrade_emits_census_and_stalls(moe_params):
    """capacity=1 with two live slots forces contention: the engine must
    report real stalls and overflow WITHOUT corrupting a stream, and the
    per-expert census must balance exactly — every routed token-slot is
    either seated (load) or overflowed (stall + retry), across all
    launches:  sum(load) + sum(overflow) ==
    L * (prompt tokens + (max_new - 1) decode ticks + stalled ticks)."""
    cap1 = dataclasses.replace(CFG, moe_experts=4, moe_capacity=1)
    eng, _ = _serve(cap1, moe_params, temp=0.8, top_k=10, slots=2,
                    num_blocks=33, block_size=8, prefill_chunk=8)
    got = eng.completions()
    for i in range(len(PROMPTS)):
        assert got[i] == _oracle(cap1, moe_params, i, 0.8, 10), f"req {i}"
    moe = eng.health()["moe"]
    assert moe["stall_slot_ticks"] >= 1  # contention really happened
    assert moe["stall_ticks"] >= 1
    # overflow counts per-layer routing events; every stalled slot
    # overflowed in at least one layer
    assert sum(moe["expert_overflow"]) >= moe["stall_slot_ticks"]
    L = cap1.num_layers
    routed = (sum(len(p) for p in PROMPTS)
              + sum(mn - 1 for mn in MAX_NEW)
              + moe["stall_slot_ticks"])
    assert (sum(moe["expert_load"]) + sum(moe["expert_overflow"])
            == L * routed)
    eng.sched.pool.check_leaks()


def test_moe_health_absorbs_into_metrics(moe_params):
    """health()["moe"] -> the declared dtg_moe_* metric names, one
    labeled series per expert (obs/metrics.py absorb_engine)."""
    from distributed_tensorflow_guide_tpu.obs import metrics

    cap1 = dataclasses.replace(CFG, moe_experts=4, moe_capacity=1)
    eng, _ = _serve(cap1, moe_params, temp=0.8, top_k=10, slots=2,
                    num_blocks=33, block_size=8, prefill_chunk=8)
    reg = metrics.Registry()
    metrics.absorb_engine(reg, eng.health())
    text = reg.to_prometheus()
    assert 'dtg_moe_expert_load_total{expert="0"}' in text
    assert 'dtg_moe_expert_overflow_total{expert="3"}' in text
    assert "dtg_moe_stall_slot_ticks_total" in text
    assert "dtg_moe_stall_ticks_total" in text


def test_moe_engine_kill_restore_resumes_bitwise(moe_params, tmp_path):
    """Snapshot/restore under the MoE model: a fresh engine restored
    from the snapshot finishes every stream bitwise (residents
    re-prefill as continuations; the dropless prefill path re-seats
    them without drops), exactly like the dense pin."""
    kw = dict(slots=2, num_blocks=33, block_size=8, prefill_chunk=8,
              temperature=0.8, top_k=10,
              snapshot_dir=str(tmp_path / "snap"))
    eng = ServeEngine(MOE_CFG, moe_params, **kw)
    _submit_all(eng)
    for _ in range(7):
        eng.step()
    label = eng.save_snapshot()
    assert label is not None
    for _ in range(3):
        eng.step()
    pre = eng.completions()
    eng.close()

    eng2 = ServeEngine(MOE_CFG, moe_params, **kw)
    assert eng2.restore_latest_snapshot() == label
    eng2.run()
    got = eng2.completions()
    for i in range(len(PROMPTS)):
        assert got[i] == _oracle(MOE_CFG, moe_params, i, 0.8, 10), \
            f"req {i}"
        assert pre[i] == got[i][:len(pre[i])]
    eng2.sched.pool.check_leaks()
    assert eng2.live_blocks() == 0
    eng2.close()


def test_non_moe_configs_compile_identical_programs(params):
    """The zero-regression gate in miniature: build_step_fns for a
    non-MoE config takes the historical branch — the jaxprs contain no
    router, no expert contraction, no moe_stats plumbing."""
    fns = build_step_fns(CFG, slots=2, num_blocks=33, block_size=8,
                         prefill_chunk=8)
    assert not fns.moe
    from distributed_tensorflow_guide_tpu.serve.engine import (
        paged_cache_shapes,
    )

    pool = jax.tree.map(lambda s: jnp.zeros(s.shape, s.dtype),
                        paged_cache_shapes(fns.cfg, 2))
    jaxpr = jax.make_jaxpr(fns.decode)(
        params, pool, jnp.zeros((2, fns.n_blk), jnp.int32),
        jnp.zeros((2,), jnp.int32), jnp.zeros((2,), jnp.int32),
        jnp.zeros((2, 2), jnp.uint32))
    assert "moe" not in str(jaxpr)
