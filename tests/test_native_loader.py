"""Native C++ data loader vs its pure-Python twin: byte-identical streams,
shard disjointness, epoch reshuffling, structured field decoding."""

import numpy as np
import pytest

from distributed_tensorflow_guide_tpu.data.native_loader import (
    ImageAugment,
    NativeRecordLoader,
    PyRecordLoader,
    epoch_permutation,
    load_native_lib,
    make_fields,
    open_record_loader,
    write_records,
)

FIELDS = make_fields({
    "image": (np.float32, (4, 4, 1)),
    "label": (np.int32, ()),
})


@pytest.fixture(scope="module")
def record_file(tmp_path_factory):
    path = tmp_path_factory.mktemp("data") / "train.records"
    rng = np.random.RandomState(0)
    n = 256
    cols = {
        "image": rng.randn(n, 4, 4, 1).astype(np.float32),
        "label": np.arange(n, dtype=np.int32),
    }
    write_records(path, cols, FIELDS)
    return path, cols


needs_native = pytest.mark.skipif(load_native_lib() is None,
                                  reason="no g++ toolchain")


def test_append_validates_record_size(tmp_path):
    """The format is headerless fixed-size records; appending with a
    different field layout must refuse instead of silently corrupting the
    stream (round-4 advisor)."""
    path = tmp_path / "x.records"
    cols = {"image": np.zeros((4, 4, 4, 1), np.float32),
            "label": np.arange(4, dtype=np.int32)}
    write_records(path, cols, FIELDS)
    # same layout appends fine (and append-to-missing == fresh write)
    write_records(path, cols, FIELDS, append=True)
    # 20-byte records over a 68-byte-record file: size check fires. (A
    # layout whose record size happens to DIVIDE the existing bytes is
    # undetectable in a headerless format — the check is best-effort.)
    other = make_fields({"vec": (np.float32, (5,))})
    with pytest.raises(ValueError, match="record_bytes"):
        write_records(path, {"vec": np.zeros((4, 5), np.float32)}, other,
                      append=True)
    fresh = tmp_path / "y.records"
    write_records(fresh, {"vec": np.zeros((4, 5), np.float32)}, other,
                  append=True)
    assert fresh.stat().st_size == 4 * 20


def test_permutation_is_deterministic_and_complete():
    p1 = epoch_permutation(100, seed=7, epoch=3)
    p2 = epoch_permutation(100, seed=7, epoch=3)
    assert np.array_equal(p1, p2)
    assert sorted(p1) == list(range(100))
    assert not np.array_equal(p1, epoch_permutation(100, seed=7, epoch=4))
    assert not np.array_equal(p1, epoch_permutation(100, seed=8, epoch=3))


def test_python_loader_decodes_fields(record_file):
    path, cols = record_file
    dl = PyRecordLoader(path, FIELDS, batch_size=32, shuffle=False)
    b = dl.next_batch()
    assert b["image"].shape == (32, 4, 4, 1)
    assert b["label"].shape == (32,)
    np.testing.assert_array_equal(b["label"], np.arange(32))
    np.testing.assert_array_equal(b["image"], cols["image"][:32])


@needs_native
def test_native_matches_python_twin(record_file):
    path, _ = record_file
    kw = dict(batch_size=16, shuffle=True, seed=11)
    native = NativeRecordLoader(path, FIELDS, **kw)
    twin = PyRecordLoader(path, FIELDS, **kw)
    assert native.batches_per_epoch == twin.batches_per_epoch == 16
    # two full epochs: crossing the boundary must reshuffle identically
    for _ in range(2 * native.batches_per_epoch):
        nb, pb = native.next_batch(), twin.next_batch()
        np.testing.assert_array_equal(nb["label"], pb["label"])
        np.testing.assert_array_equal(nb["image"], pb["image"])
    native.close()


@needs_native
def test_native_shards_are_disjoint_and_cover(record_file):
    path, _ = record_file
    seen = []
    for shard in range(4):
        dl = NativeRecordLoader(path, FIELDS, batch_size=16, shard_id=shard,
                                num_shards=4, shuffle=True, seed=5)
        labels = np.concatenate([dl.next_batch()["label"]
                                 for _ in range(dl.batches_per_epoch)])
        seen.append(labels)
        dl.close()
    allseen = np.concatenate(seen)
    assert len(allseen) == 256
    assert len(set(allseen.tolist())) == 256  # disjoint cover, no dupes


@needs_native
def test_native_epoch_order_differs(record_file):
    path, _ = record_file
    dl = NativeRecordLoader(path, FIELDS, batch_size=64, shuffle=True, seed=1)
    e0 = np.concatenate([dl.next_batch()["label"] for _ in range(4)])
    e1 = np.concatenate([dl.next_batch()["label"] for _ in range(4)])
    dl.close()
    assert sorted(e0.tolist()) == sorted(e1.tolist()) == list(range(256))
    assert not np.array_equal(e0, e1)


@needs_native
def test_native_rejects_bad_files(tmp_path):
    bad = tmp_path / "bad.records"
    bad.write_bytes(b"\x00" * 37)  # not a whole number of records
    with pytest.raises(ValueError):
        NativeRecordLoader(bad, FIELDS, batch_size=4)


def test_open_record_loader_falls_back(record_file, monkeypatch):
    path, _ = record_file
    import distributed_tensorflow_guide_tpu.data.native_loader as nl

    monkeypatch.setattr(nl, "load_native_lib", lambda: None)
    dl = open_record_loader(path, FIELDS, 16, shuffle=False, prefetch=2)
    assert isinstance(dl, PyRecordLoader)
    assert dl.next_batch()["label"].shape == (16,)


@needs_native
def test_native_pooled_gather_large_records(tmp_path):
    # batch*record > 64KB exercises the persistent worker pool (small
    # batches are copied inline by the producer)
    fields = make_fields({"x": (np.float32, (1024,))})  # 4KB records
    rng = np.random.RandomState(1)
    cols = {"x": rng.randn(128, 1024).astype(np.float32)}
    path = tmp_path / "big.records"
    write_records(path, cols, fields)
    kw = dict(batch_size=32, shuffle=True, seed=9)
    native = NativeRecordLoader(path, fields, n_threads=4, **kw)
    twin = PyRecordLoader(path, fields, **kw)
    for _ in range(3 * native.batches_per_epoch):
        np.testing.assert_array_equal(native.next_batch()["x"],
                                      twin.next_batch()["x"])
    native.close()


@needs_native
def test_native_prefetch_throughput_smoke(record_file):
    # not a benchmark — just proves the ring survives rapid consumption
    path, _ = record_file
    dl = NativeRecordLoader(path, FIELDS, batch_size=8, prefetch=8,
                            n_threads=2, shuffle=True, seed=3)
    for _ in range(200):  # ~6 epochs through the rollover path
        dl.next_batch()
    dl.close()


@needs_native
def test_native_loader_feeds_pipelined_lm(tmp_path):
    """Composition: the C++ record stream feeds the GPT-2 pipeline strategy
    (token records -> microbatch reshape -> dp x pp mesh), not just MNIST
    DP — the reference's data path works with every strategy family."""
    import jax
    import jax.numpy as jnp
    import optax

    from distributed_tensorflow_guide_tpu.core.mesh import (
        MeshSpec,
        build_mesh,
    )
    from distributed_tensorflow_guide_tpu.models.transformer import (
        TransformerConfig,
    )
    from distributed_tensorflow_guide_tpu.parallel.pipeline import PipelinedLM

    cfg = TransformerConfig(
        vocab_size=64, num_layers=4, num_heads=2, d_model=32, d_ff=64,
        max_len=16, causal=True, dtype=jnp.float32,
    )
    M, mb = 2, 2  # microbatches x microbatch rows per data shard
    mesh = build_mesh(MeshSpec(data=2, pipe=4))
    lm = PipelinedLM(mesh, cfg, num_microbatches=M)
    params = lm.init_params(jax.random.PRNGKey(0))
    tx = optax.adam(1e-3)
    opt_state = lm.init_opt_state(tx, params)
    step = lm.make_train_step(tx, params, donate=False)

    # token records on disk -> native stream -> global batch (B, S)
    rng = np.random.RandomState(0)
    n_records = 64
    fields = make_fields({"tokens": (np.int32, (cfg.max_len,))})
    path = tmp_path / "tokens.rec"
    write_records(path, {
        "tokens": rng.randint(0, cfg.vocab_size,
                              (n_records, cfg.max_len)).astype(np.int32)
    }, fields)

    B = M * mb * mesh.shape["data"]
    loader = NativeRecordLoader(path, fields, batch_size=B, seed=3)
    losses = []
    for _ in range(3):
        batch = loader.next_batch()
        _opt, params_new, mets = step(opt_state, params,
                                      jnp.asarray(batch["tokens"]))
        opt_state, params = _opt, params_new
        losses.append(float(mets["loss"]))
    assert all(np.isfinite(losses)), losses
    assert loader.num_records == n_records
    loader.close()


# -- train-time image augmentation (round-5: crop+flip in the loader tier) ---

AUG_FIELDS = make_fields({
    "image": (np.uint8, (40, 40, 3)),
    "label": (np.int32, ()),
})
AUG = ImageAugment(in_shape=(40, 40, 3), crop=(32, 32), hflip=True)


@pytest.fixture(scope="module")
def aug_file(tmp_path_factory):
    path = tmp_path_factory.mktemp("aug") / "imgs.records"
    rng = np.random.RandomState(3)
    n = 64
    cols = {"image": rng.randint(0, 256, (n, 40, 40, 3)).astype(np.uint8),
            "label": np.arange(n, dtype=np.int32)}
    write_records(path, cols, AUG_FIELDS)
    return path, cols


@needs_native
def test_augmented_native_matches_python_twin(aug_file):
    """The bit-identical-streams contract extends to augmentation: the C++
    gather-copy crop/flip and the Python twin agree byte-for-byte, across
    an epoch boundary (epoch is part of the draw seed)."""
    path, _ = aug_file
    kw = dict(batch_size=8, shuffle=True, seed=5, augment=AUG)
    nat = NativeRecordLoader(path, AUG_FIELDS, **kw)
    py = PyRecordLoader(path, AUG_FIELDS, **kw)
    assert nat.batches_per_epoch == py.batches_per_epoch == 8
    for i in range(20):  # 2.5 epochs
        a, b = nat.next_batch(), py.next_batch()
        assert a["image"].shape == (8, 32, 32, 3)
        np.testing.assert_array_equal(a["image"], b["image"], err_msg=str(i))
        np.testing.assert_array_equal(a["label"], b["label"])
    nat.close()


def test_augmentation_pinned_to_seed_epoch_index(aug_file):
    """The determinism contract: draws are a pure function of
    (seed, epoch, record index) — invariant to shuffle order; changed by
    epoch and by seed."""
    path, cols = aug_file
    # unshuffled epoch 0: record r of batch 0 is global index r
    py = PyRecordLoader(path, AUG_FIELDS, batch_size=64, shuffle=False,
                        seed=5, augment=AUG)
    plain = py.next_batch()

    # same records reached through a SHUFFLED loader get the SAME crops:
    # find each record by label and compare
    sh = PyRecordLoader(path, AUG_FIELDS, batch_size=64, shuffle=True,
                        seed=5, augment=AUG)
    shuffled = sh.next_batch()
    order = np.argsort(shuffled["label"])
    np.testing.assert_array_equal(shuffled["image"][order], plain["image"])

    # epoch 1 re-crops (epoch is in the seed): some record must differ
    e1 = py.next_batch()  # advances to epoch 1 (64 = one full epoch)
    assert py._epoch == 1
    assert not np.array_equal(e1["image"], plain["image"])

    # a different seed re-crops too
    other = PyRecordLoader(path, AUG_FIELDS, batch_size=64, shuffle=False,
                           seed=6, augment=AUG)
    assert not np.array_equal(other.next_batch()["image"], plain["image"])

    # crops are genuine views of the stored image: every augmented image
    # appears somewhere in its source (check one record exhaustively)
    src = cols["image"][0]
    out = plain["image"][0]
    found = any(
        np.array_equal(src[y:y + 32, x:x + 32], cand)
        for cand in (out, out[:, ::-1])
        for y in range(9) for x in range(9)
    )
    assert found


def test_augment_spec_validation(aug_file):
    path, _ = aug_file
    with pytest.raises(ValueError, match="must fit"):
        ImageAugment(in_shape=(40, 40, 3), crop=(41, 32))
    # leading field must be the uint8 image at the declared shape
    bad = make_fields({"label": (np.int32, ()),
                       "image": (np.uint8, (40, 40, 3))})
    with pytest.raises(ValueError, match="leading uint8 image"):
        PyRecordLoader(path, bad, batch_size=8, augment=AUG)
