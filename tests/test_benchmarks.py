"""Benchmark-suite smoke tests: every judged-config bench runs end to end on
fake CPU devices and prints a well-formed JSON result line.

(The numbers only mean something on the real chip; these tests pin the
contract — the scripts stay runnable and the one-line JSON schema stays
intact — which is what the driver and judge consume.)
"""

import json
import os
import subprocess
import sys
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parents[1]
BENCH = REPO / "benchmarks"

from benchmarks.run_all import SMOKE  # noqa: E402  (one source of smoke cfgs)

CASES = sorted(SMOKE.items())


@pytest.mark.parametrize("script,args", CASES,
                         ids=[c[0].removeprefix("bench_").removesuffix(".py")
                              for c in CASES])
def test_bench_smoke(script, args):
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)  # benches set their own device counts
    r = subprocess.run(
        [sys.executable, str(BENCH / script), *args],
        capture_output=True, text=True, timeout=420, env=env, cwd=REPO,
    )
    assert r.returncode == 0, r.stdout + r.stderr
    line = r.stdout.strip().splitlines()[-1]
    result = json.loads(line)
    # the contract keys must be present; benches may add evidence keys
    # (bench.py itself adds trials/spread_pct, fsdp_memory adds the
    # replicated-DP comparison)
    assert {"metric", "value", "unit", "vs_baseline"} <= set(result)
    assert result["value"] > 0


# ---- run_battery empty-artifact guard (ADVICE round 5) ----------------------
# A zero-byte battery_*.jsonl got committed as if it were capture evidence;
# run_battery now refuses to create a record-free artifact.


def test_run_battery_refuses_empty_artifact(tmp_path, monkeypatch):
    from benchmarks import run_battery

    out = tmp_path / "battery_empty.jsonl"
    monkeypatch.setattr(run_battery, "BATTERY", [])
    monkeypatch.setattr(sys, "argv",
                        ["run_battery.py", "--out", str(out)])
    with pytest.raises(SystemExit) as e:
        run_battery.main()
    assert "empty" in str(e.value)
    assert not out.exists()


# ---- bench.py orchestrator (round-2 hardening) ------------------------------
# The driver's round-1 capture died on a hung/unavailable axon backend
# (BENCH_r01.json rc=1). bench.py now probes the backend in a child process
# with a hard timeout, retries with backoff, and on final failure prints one
# diagnostic JSON line and exits 1 fast. These tests pin that contract.

import importlib.util

_spec = importlib.util.spec_from_file_location("bench_root", REPO / "bench.py")
bench_root = importlib.util.module_from_spec(_spec)
_spec.loader.exec_module(bench_root)


def test_bench_extract_json_line():
    out = "noise\n{\"bad json\n" + json.dumps(
        {"metric": "m", "value": 1.0, "unit": "u", "vs_baseline": None}
    ) + "\ntrailing log line"
    got = bench_root._extract_json_line(out)
    assert got is not None and got["metric"] == "m"
    assert bench_root._extract_json_line("no json here") is None
    # A JSON line missing the contract keys is rejected.
    assert bench_root._extract_json_line('{"foo": 1}') is None


def test_bench_orchestrator_fails_fast_with_diagnostic_line():
    env = dict(os.environ)
    env.update(
        BENCH_MAX_ATTEMPTS="1",
        BENCH_PROBE_TIMEOUT="30",
        BENCH_RUN_TIMEOUT="30",
        # Deterministic probe failure: jax.devices() raises on an unknown
        # platform name, no matter how healthy the real backend is. (The
        # previous version relied on a 3s timeout beating `import jax`,
        # which a warm page cache could win — then the full bench ran and
        # blew the outer 120s timeout.)
        JAX_PLATFORMS="no_such_platform",
        # This test pins the backend-probe failure path; skip the round-5
        # relay pre-probe so it runs even on a host with no relay listeners.
        BENCH_FORCE_FULL_PROBE="1",
    )
    r = subprocess.run(
        [sys.executable, str(REPO / "bench.py")],
        capture_output=True, text=True, timeout=120, env=env, cwd=REPO,
    )
    assert r.returncode == 1
    line = r.stdout.strip().splitlines()[-1]
    result = json.loads(line)
    assert result["value"] is None
    assert "error" in result and "unavailable" in result["error"].lower()
    assert {"metric", "value", "unit", "vs_baseline"} <= set(result)


# ---- relay pre-probe (round 5) ----------------------------------------------
# Rounds 3 and 4 each burned the driver's whole capture budget (705s of
# timed-out backend probes) discovering the axon tunnel was dead. The
# pre-probe reads /proc/net/tcp for the relay's loopback listeners and turns
# that into a <5s diagnosis.


def test_relay_listener_ports_parses_proc_format(tmp_path):
    # 0x1F93 == 8083 (a port the live relay was observed on); 0x0900 == 2304.
    proc = tmp_path / "tcp"
    proc.write_text(
        "  sl  local_address rem_address   st ...\n"
        # loopback LISTEN in range -> counted
        "   0: 0100007F:1F93 00000000:0000 0A 0 0 0 0 0 0 0\n"
        # wildcard-bound LISTEN in range -> not loopback, excluded
        "   1: 00000000:1F94 00000000:0000 0A 0 0 0 0 0 0 0\n"
        # loopback LISTEN out of range -> excluded
        "   2: 0100007F:0900 00000000:0000 0A 0 0 0 0 0 0 0\n"
        # loopback ESTABLISHED in range -> excluded (st 01)
        "   3: 0100007F:1F95 0100007F:BC8F 01 0 0 0 0 0 0 0\n"
    )
    assert bench_root.relay_listener_ports(paths=(str(proc),)) == [8083]
    # Unreadable tables are "unknown", not "zero listeners" — orchestrate
    # must fall through to the backend probes rather than fast-fail.
    assert bench_root.relay_listener_ports(paths=("/no/such/file",)) is None


def test_bench_preprobe_fast_fails_without_relay(monkeypatch, capsys):
    monkeypatch.setattr(bench_root, "relay_listener_ports", lambda: [])
    monkeypatch.delenv("BENCH_FORCE_FULL_PROBE", raising=False)
    monkeypatch.setattr(bench_root.time, "sleep", lambda s: None)  # 3 checks, no wait
    rc = bench_root.orchestrate()
    assert rc == 1
    line = capsys.readouterr().out.strip().splitlines()[-1]
    result = json.loads(line)
    assert result["value"] is None
    assert "relay" in result["error"]
    assert {"metric", "value", "unit", "vs_baseline"} <= set(result)


def test_bench_preprobe_unknown_falls_through_to_probes(monkeypatch, capsys):
    # /proc/net/tcp unreadable -> pre-probe must NOT fast-fail; the backend
    # probes run (here: a stub that fails once) and produce the usual
    # "unavailable" diagnostic, proving the old path was taken.
    monkeypatch.setattr(bench_root, "relay_listener_ports", lambda: None)
    monkeypatch.delenv("BENCH_FORCE_FULL_PROBE", raising=False)
    monkeypatch.setattr(bench_root.time, "sleep", lambda s: None)
    monkeypatch.setattr(bench_root, "MAX_ATTEMPTS", 1)
    monkeypatch.setattr(bench_root, "_child", lambda arg, timeout: (1, "boom"))
    rc = bench_root.orchestrate()
    assert rc == 1
    line = capsys.readouterr().out.strip().splitlines()[-1]
    result = json.loads(line)
    assert "unavailable" in result["error"]
