"""Benchmark-suite smoke tests: every judged-config bench runs end to end on
fake CPU devices and prints a well-formed JSON result line.

(The numbers only mean something on the real chip; these tests pin the
contract — the scripts stay runnable and the one-line JSON schema stays
intact — which is what the driver and judge consume.)
"""

import json
import os
import subprocess
import sys
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parents[1]
BENCH = REPO / "benchmarks"

from benchmarks.run_all import SMOKE  # noqa: E402  (one source of smoke cfgs)

CASES = sorted(SMOKE.items())


@pytest.mark.parametrize("script,args", CASES,
                         ids=[c[0].removeprefix("bench_").removesuffix(".py")
                              for c in CASES])
def test_bench_smoke(script, args):
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)  # benches set their own device counts
    r = subprocess.run(
        [sys.executable, str(BENCH / script), *args],
        capture_output=True, text=True, timeout=420, env=env, cwd=REPO,
    )
    assert r.returncode == 0, r.stdout + r.stderr
    line = r.stdout.strip().splitlines()[-1]
    result = json.loads(line)
    assert set(result) == {"metric", "value", "unit", "vs_baseline"}
    assert result["value"] > 0
