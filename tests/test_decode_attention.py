"""Decode-attention kernel layer (ops/decode_attention.py): numerical
parity of every swept KV-block candidate against the dense oracle (the
test_autotune.py pattern — the sweep optimizes time, never correctness),
the int8 quantization contract, the length-masking robustness the
length-aware grid rests on, and the autotune-table plumbing (CPU
defaults-only hermeticity included).

Kernels run in interpret mode on the CPU test backend — the numerics are
the kernel's own; only the timings need a chip.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_tensorflow_guide_tpu.ops import autotune
from distributed_tensorflow_guide_tpu.ops import decode_attention as DA

B, H, S, HD = 2, 3, 128, 16


@pytest.fixture(autouse=True)
def _isolated_table(isolated_autotune_table):
    yield


def _cache(seed=0, s=S):
    r = np.random.RandomState(seed)
    k = jnp.asarray(r.randn(B, H, s, HD), jnp.float32)
    v = jnp.asarray(r.randn(B, H, s, HD), jnp.float32)
    return k, v


def _q(c=1, seed=3):
    r = np.random.RandomState(seed)
    return jnp.asarray(r.randn(B, c, H, HD), jnp.float32)


def _dense_oracle(q, k, v, index, s=S):
    """The dense full-cache read the kernel must reproduce: same mask
    predicate, f32 softmax."""
    c = q.shape[1]
    scores = jnp.einsum("bqhd,bhkd->bhqk", q, k) / jnp.sqrt(HD)
    mask = jnp.arange(s)[None, :] <= (index + jnp.arange(c))[:, None]
    scores = jnp.where(mask[None, None], scores, jnp.finfo(jnp.float32).min)
    probs = jax.nn.softmax(scores.astype(jnp.float32), -1)
    return jnp.einsum("bhqk,bhkd->bqhd", probs, v)


# ---- numerical parity of the sweep space ------------------------------------


def test_every_swept_candidate_matches_dense_oracle():
    """Every (8, blk_k) candidate the decode sweep may ever pick must be
    numerically exact against the dense oracle — single-token decode at an
    early, a mid-cache and a full-cache index."""
    k, v = _cache()
    q = _q()
    cands = autotune.candidate_blocks(autotune.DECODE_KERNEL, s=S, d=HD,
                                      dtype=jnp.float32)
    assert cands and all(bq == autotune.DECODE_CHUNK_SUBLANES
                         for bq, _ in cands)
    for index in (0, 37, S - 1):
        ref = _dense_oracle(q, k, v, index)
        for _, bk in cands:
            got = DA.decode_attention(q, k, v, index, blk_k=bk)
            np.testing.assert_allclose(got, ref, atol=1e-5, rtol=1e-5,
                                       err_msg=f"blk_k {bk} index {index}")


def test_prefill_chunk_parity_and_padded_rows_sliced():
    """A multi-token chunk (prefill / speculative verify) through the same
    kernel: intra-chunk causality via the shared predicate, sublane-padded
    rows sliced off."""
    k, v = _cache(1)
    for c, index in ((5, 0), (4, 60), (9, 100)):
        q = _q(c)
        ref = _dense_oracle(q, k, v, index)
        got = DA.decode_attention(q, k, v, index, blk_k=64)
        assert got.shape == (B, c, H, HD)
        np.testing.assert_allclose(got, ref, atol=1e-5, rtol=1e-5)


def test_int8_parity_at_every_candidate():
    """Quantized kernel vs the dense oracle on the DEQUANTIZED cache: the
    fused dequant (scales folded into score and probability columns) must
    equal materialized dequantization exactly."""
    k, v = _cache(2)
    k8, ks = DA.quantize_kv(k)
    v8, vs = DA.quantize_kv(v)
    kd = k8.astype(jnp.float32) * ks[..., None]
    vd = v8.astype(jnp.float32) * vs[..., None]
    q = _q(seed=4)
    ref = _dense_oracle(q, kd, vd, 77)
    for _, bk in autotune.candidate_blocks(autotune.DECODE_KERNEL, s=S,
                                           d=HD, dtype=jnp.int8):
        got = DA.decode_attention(q, k8, v8, 77,
                                  key_scale=ks[:, :, None, :],
                                  value_scale=vs[:, :, None, :], blk_k=bk)
        np.testing.assert_allclose(got, ref, atol=1e-5, rtol=1e-5,
                                   err_msg=f"blk_k {bk}")


def test_garbage_beyond_length_cannot_leak():
    """The not-yet-written cache region is hidden by the mask AND skipped
    by the length-aware grid: poisoning every slot past the length with
    huge finite garbage (what stale slots actually hold — rejected
    speculative drafts, old sequences — is always finite) must not perturb
    a single output bit vs the zero-filled cache."""
    k, v = _cache(5)
    q = _q(seed=6)
    index = 41  # length 42: last live 64-block is [0, 64); [64, 128) dead
    poison = jnp.full_like(k, 1e6).at[:, :, :index + 1].set(
        k[:, :, :index + 1])
    vpoison = jnp.full_like(v, -1e6).at[:, :, :index + 1].set(
        v[:, :, :index + 1])
    want = DA.decode_attention(q, k, v, index, blk_k=64)
    got = DA.decode_attention(q, poison, vpoison, index, blk_k=64)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


# ---- quantization contract --------------------------------------------------


def test_quantize_kv_error_bound_and_zero_vector():
    r = np.random.RandomState(7)
    x = jnp.asarray(r.randn(4, 5, 64), jnp.float32) * 3.0
    q8, scale = DA.quantize_kv(x)
    assert q8.dtype == jnp.int8 and scale.shape == x.shape[:-1]
    deq = q8.astype(jnp.float32) * scale[..., None]
    # symmetric round-to-nearest: error <= scale/2 per element
    assert np.all(np.abs(np.asarray(deq - x))
                  <= np.asarray(scale)[..., None] / 2 + 1e-7)
    z8, zscale = DA.quantize_kv(jnp.zeros((2, 3, 8)))
    np.testing.assert_array_equal(np.asarray(z8), 0)
    np.testing.assert_array_equal(np.asarray(zscale), 1.0)  # never 0/0


# ---- table plumbing ---------------------------------------------------------


def test_block_resolution_consults_table_and_survives_stale_entries():
    # seeded entry redirects the default resolution (cpu platform key —
    # only tests can seed it; the file path is closed by hermeticity)
    autotune._mem[autotune._key(autotune.DECODE_KERNEL, 0, 0, S, HD,
                                "int8", False, "cpu")] = {
        "blk_q": 8, "blk_k": 64}
    assert DA.decode_blk_k_for(b=B, h=H, s=S, d=HD, dtype=jnp.int8) == 64
    # a stale edge that no longer divides the cache is ignored
    autotune._mem[autotune._key(autotune.DECODE_KERNEL, 0, 0, S, HD,
                                "float32", False, "cpu")] = {
        "blk_q": 8, "blk_k": 96}
    blk = DA.decode_blk_k_for(b=B, h=H, s=S, d=HD, dtype=jnp.float32)
    assert S % blk == 0 and blk % 8 == 0
    # miss on an odd cache length falls down the divisor ladder
    assert DA.decode_blk_k_for(b=1, h=1, s=32, d=HD,
                               dtype=jnp.float32) == 32


def test_decode_sweep_mechanism_and_cpu_hermeticity():
    calls = []

    def measure(kern, blocks):
        calls.append(blocks)
        return 1.0 / blocks[1]  # favors the widest KV block

    best = autotune.ensure_tuned(autotune.DECODE_KERNEL, b=1, h=2, s=S,
                                 d=HD, dtype=jnp.int8, causal=False,
                                 measure=measure, platform="tpu")
    cands = autotune.candidate_blocks(autotune.DECODE_KERNEL, s=S, d=HD,
                                      dtype=jnp.int8)
    assert len(calls) == len(cands) and best == (8, max(
        bk for _, bk in cands))
    # no re-sweep on a hit; the generic entry serves other batch/heads
    again = autotune.ensure_tuned(autotune.DECODE_KERNEL, b=1, h=2, s=S,
                                  d=HD, dtype=jnp.int8, causal=False,
                                  measure=measure, platform="tpu")
    assert again == best and len(calls) == len(cands)
    assert DA.decode_blk_k_for(b=5, h=9, s=S, d=HD, dtype=jnp.int8,
                               platform="tpu") == best[1]
    # the CPU platform refuses to sweep (tier-1 defaults-only contract)
    with pytest.raises(RuntimeError, match="defaults-only"):
        DA.ensure_decode_tuned(b=1, h=2, s=S, d=HD, dtype=jnp.int8)


def test_runner_executes_and_matches_oracle():
    """The sweep/microbench runner drives the REAL kernel on a full cache;
    its int8 variant must agree with the dequantized oracle built from the
    same seeded operands."""
    fn = DA.make_decode_runner(64, b=1, h=2, s=64, d=16, dtype=jnp.int8)
    out = jax.block_until_ready(fn())
    assert out.shape == (1, 1, 2, 16)
    # rebuild the runner's operands (same seed path) for the oracle
    keys = jax.random.split(jax.random.PRNGKey(0), 3)
    q = jax.random.normal(keys[0], (1, 1, 2, 16), jnp.float32).astype(
        jnp.bfloat16)
    kf = jax.random.normal(keys[1], (1, 2, 64, 16), jnp.float32)
    vf = jax.random.normal(keys[2], (1, 2, 64, 16), jnp.float32)
    k8, ks = DA.quantize_kv(kf)
    v8, vs = DA.quantize_kv(vf)
    kd = k8.astype(jnp.float32) * ks[..., None]
    vd = v8.astype(jnp.float32) * vs[..., None]
    scores = jnp.einsum("bqhd,bhkd->bhqk", q.astype(jnp.float32), kd) \
        / jnp.sqrt(16.0)
    mask = jnp.arange(64)[None, :] <= jnp.asarray([63])[:, None]
    scores = jnp.where(mask[None, None], scores,
                       jnp.finfo(jnp.float32).min)
    ref = jnp.einsum("bhqk,bhkd->bqhd",
                     jax.nn.softmax(scores, -1), vd)
    np.testing.assert_allclose(np.asarray(out, np.float32), ref,
                               atol=2e-2, rtol=2e-2)  # bf16 q + bf16 out
    f32fn = DA.make_decode_runner(64, b=1, h=2, s=64, d=16,
                                  dtype=jnp.float32)
    assert jax.block_until_ready(f32fn()).shape == (1, 1, 2, 16)


# ---- roofline byte model ----------------------------------------------------


def test_decode_kernel_hbm_bytes_closed_form():
    kw = dict(b=2, h=3, s=128, d=16)
    bf16 = DA.decode_kernel_hbm_bytes(dtype=jnp.bfloat16, **kw)
    i8 = DA.decode_kernel_hbm_bytes(dtype=jnp.int8, **kw)
    cache_elems = 2 * 2 * 3 * 128 * 16  # k and v
    qo = 2 * 2 * 3 * 1 * 16 * 2  # q + out, bf16
    assert bf16 == cache_elems * 2 + qo
    # int8 halves the cache term twice over bf16, plus the f32 scale rows
    assert i8 == cache_elems * 1 + 2 * 2 * 3 * 128 * 4 + qo
    # the length-aware model charges only live (block-rounded) slots
    short = DA.decode_kernel_hbm_bytes(dtype=jnp.bfloat16,
                                       effective_len=32, **kw)
    assert short == 2 * 2 * 3 * 32 * 16 * 2 + qo


def test_decode_flop_model_single_q_tile():
    """The decode grid has ONE fixed q tile — the FLOP model must charge
    s/blk_k KV blocks once, not the training kernels' (s/blk_q) x
    (s/blk_k) grid (which would inflate throughput ~s/blk_q-fold)."""
    got = autotune.kernel_flops(autotune.DECODE_KERNEL, b=2, h=3, s=1024,
                                d=64, blocks=(8, 256), causal=False)
    dp = autotune.padded_head_dim(64)
    assert got == 2.0 * 2 * 8 * 256 * dp * (1024 // 256) * 2 * 3
    # the flash forward at the same key is the full-grid count — strictly
    # larger (the bug this pins against)
    full = autotune.kernel_flops("flash_fwd", b=2, h=3, s=1024, d=64,
                                 blocks=(8, 256), causal=False)
    assert full == got * (1024 // 8)


def test_chunk_cap_routes_oversized_prefill_to_dense():
    """The q tile is unblocked, so chunks past DECODE_MAX_CHUNK are
    unsupported by design (VMEM) — supported() gates them out and
    decode_attention refuses them; _decode_attend routes them dense."""
    assert DA.supported(1024, 256, chunk=1)
    assert DA.supported(1024, 256, chunk=autotune.DECODE_MAX_CHUNK)
    assert not DA.supported(1024, 256, chunk=autotune.DECODE_MAX_CHUNK + 1)
    # an over-cap prefill chunk is refused outright (callers gate on
    # supported() first; max_len 256 so the chunk fits the cache)
    s2 = 256
    k2, v2 = _cache(8, s=s2)
    q_big = _q(c=autotune.DECODE_MAX_CHUNK + 1, seed=9)
    with pytest.raises(ValueError, match="chunk"):
        DA.decode_attention(q_big, k2, v2, 0, blk_k=64)


def test_vmem_model_and_candidates_valid():
    for s in (128, 256, 1024):
        cands = autotune.candidate_blocks(autotune.DECODE_KERNEL, s=s,
                                          d=64, dtype=jnp.int8)
        assert cands, s
        for bq, bk in cands:
            assert bq == autotune.DECODE_CHUNK_SUBLANES
            assert s % bk == 0 and bk % 8 == 0
            assert autotune.kernel_vmem_bytes(
                autotune.DECODE_KERNEL, bq, bk, 128,
                jnp.int8) <= autotune.VMEM_BUDGET_BYTES


# ---- paged pool variant (serve/) --------------------------------------------


def _paged(k, v, bs, *, ks=None, vs=None, seed=11):
    """Scatter dense (B, H, S, hd) caches into a SHUFFLED physical pool
    plus the block tables mapping them back — non-identity tables are the
    point: the kernel must resolve every tile through the indirection."""
    k, v = np.asarray(k), np.asarray(v)
    b, h, s, hd = k.shape
    n_blk = s // bs
    perm = np.random.RandomState(seed).permutation(b * n_blk)
    nb = b * n_blk + 1  # + the trash block convention
    kp = np.zeros((nb, h, bs, hd), k.dtype)
    vp = np.zeros((nb, h, bs, hd), v.dtype)
    ksp = np.ones((nb, h, 1, bs), np.float32)
    vsp = np.ones((nb, h, 1, bs), np.float32)
    tables = np.zeros((b, n_blk), np.int32)
    for bi in range(b):
        for j in range(n_blk):
            p = int(perm[bi * n_blk + j])
            sl = slice(j * bs, (j + 1) * bs)
            kp[p], vp[p] = k[bi, :, sl], v[bi, :, sl]
            if ks is not None:
                ksp[p, :, 0] = np.asarray(ks)[bi, :, sl]
                vsp[p, :, 0] = np.asarray(vs)[bi, :, sl]
            tables[bi, j] = p
    out = (jnp.asarray(kp), jnp.asarray(vp), jnp.asarray(tables))
    if ks is not None:
        out += (jnp.asarray(ksp), jnp.asarray(vsp))
    return out


def test_paged_kernel_matches_dense_oracle_through_shuffled_tables():
    """Single-token decode against the paged pool: per-request lengths,
    shuffled block tables, parity with the dense oracle on the contiguous
    view the tables encode."""
    k, v = _cache(10)
    q = _q(seed=12)
    bs = 32
    kp, vp, tables = _paged(k, v, bs)
    for lengths in ([S, S], [42, 97], [1, S]):
        got = DA.paged_decode_attention(
            q, kp, vp, tables, jnp.asarray(lengths, jnp.int32),
            block_size=bs, blk_k=16)
        for bi, ln in enumerate(lengths):
            ref = _dense_oracle(q[bi:bi + 1], k[bi:bi + 1],
                                v[bi:bi + 1], ln - 1)
            np.testing.assert_allclose(
                got[bi:bi + 1], ref, atol=1e-5, rtol=1e-5,
                err_msg=f"req {bi} length {ln}")


def test_paged_chunk_parity():
    """A C>1 chunk (chunked prefill / the serve prefill program) through
    the paged kernel: request b's chunk occupies logical positions
    [lengths[b] - C, lengths[b]) with intra-chunk causality."""
    k, v = _cache(16)
    c = 4
    q = _q(c=c, seed=17)
    bs = 32
    kp, vp, tables = _paged(k, v, bs)
    lengths = [60, S]
    got = DA.paged_decode_attention(
        q, kp, vp, tables, jnp.asarray(lengths, jnp.int32),
        block_size=bs, blk_k=16)
    assert got.shape == (B, c, H, HD)
    for bi, ln in enumerate(lengths):
        ref = _dense_oracle(q[bi:bi + 1], k[bi:bi + 1], v[bi:bi + 1],
                            ln - c)
        np.testing.assert_allclose(got[bi:bi + 1], ref, atol=1e-5,
                                   rtol=1e-5, err_msg=f"req {bi}")


def test_paged_int8_parity():
    """Quantized pool (int8 blocks + f32 scale blocks in the pool's
    (N, H, 1, bs) layout) vs the dense oracle on the dequantized cache."""
    k, v = _cache(13)
    k8, ks = DA.quantize_kv(k)
    v8, vs = DA.quantize_kv(v)
    kd = k8.astype(jnp.float32) * ks[..., None]
    vd = v8.astype(jnp.float32) * vs[..., None]
    q = _q(seed=14)
    bs = 32
    k8p, v8p, tables, ksp, vsp = _paged(k8, v8, bs, ks=ks, vs=vs)
    lengths = [77, 33]
    got = DA.paged_decode_attention(
        q, k8p, v8p, tables, jnp.asarray(lengths, jnp.int32),
        key_scale_pool=ksp, value_scale_pool=vsp, block_size=bs,
        blk_k=16)
    for bi, ln in enumerate(lengths):
        ref = _dense_oracle(q[bi:bi + 1], kd[bi:bi + 1], vd[bi:bi + 1],
                            ln - 1)
        np.testing.assert_allclose(got[bi:bi + 1], ref, atol=1e-5,
                                   rtol=1e-5, err_msg=f"req {bi}")


def test_paged_dead_blocks_cannot_leak():
    """Pool contents past a request's length — whole dead blocks AND the
    dead tail of its last partially-live block (what freed/stale blocks
    actually hold) — must not perturb one output bit."""
    k, v = _cache(14)
    q = _q(seed=15)
    bs = 32
    kp, vp, tables = _paged(k, v, bs)
    lengths = jnp.asarray([42, 10], jnp.int32)
    want = DA.paged_decode_attention(q, kp, vp, tables, lengths,
                                     block_size=bs, blk_k=16)
    kp2, vp2 = np.asarray(kp).copy(), np.asarray(vp).copy()
    for bi in range(B):
        ln = int(lengths[bi])
        for j in range(tables.shape[1]):
            p = int(tables[bi, j])
            if j * bs >= ln:  # fully dead block
                kp2[p], vp2[p] = 1e6, -1e6
            elif (j + 1) * bs > ln:  # partially live: poison the tail
                kp2[p, :, ln - j * bs:] = 1e6
                vp2[p, :, ln - j * bs:] = -1e6
    got = DA.paged_decode_attention(q, jnp.asarray(kp2),
                                    jnp.asarray(vp2), tables, lengths,
                                    block_size=bs, blk_k=16)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_paged_blk_k_resolution_and_supported():
    # a tuned edge that divides the pool block is honored
    autotune._mem[autotune._key(autotune.PAGED_DECODE_KERNEL, 0, 0, S,
                                HD, "float32", False, "cpu")] = {
        "blk_q": 8, "blk_k": 16}
    assert DA.paged_decode_blk_k_for(b=B, h=H, s=S, d=HD,
                                     dtype=jnp.float32,
                                     block_size=32) == 16
    # a tuned edge that would straddle physical blocks is ignored: the
    # divisor ladder picks the largest default that fits the block
    autotune._mem[autotune._key(autotune.PAGED_DECODE_KERNEL, 0, 0, S,
                                HD, "float32", False, "cpu")] = {
        "blk_q": 8, "blk_k": 64}
    assert DA.paged_decode_blk_k_for(b=B, h=H, s=S, d=HD,
                                     dtype=jnp.float32,
                                     block_size=32) == 32
    assert DA.paged_supported(S, 32, 16)
    assert not DA.paged_supported(S, 32, 64)  # tile straddles blocks
    assert not DA.paged_supported(120, 32, 16)  # ragged final block
    assert not DA.paged_supported(S, 32, 16,
                                  chunk=autotune.DECODE_MAX_CHUNK + 1)
    # a straddling blk_k is refused outright at call time
    with pytest.raises(ValueError, match="unsupported"):
        DA.paged_decode_attention(
            _q(seed=19), jnp.zeros((9, H, 32, HD)),
            jnp.zeros((9, H, 32, HD)),
            jnp.zeros((B, 4), jnp.int32), jnp.asarray([1, 1]),
            block_size=32, blk_k=64)


def test_paged_sweep_skips_straddling_candidates_and_cpu_refusal():
    # the CPU platform refuses to sweep (tier-1 defaults-only contract,
    # same as the contiguous decode sweep)
    with pytest.raises(RuntimeError, match="defaults-only"):
        DA.ensure_paged_decode_tuned(b=1, h=1, s=S, d=16,
                                     dtype=jnp.float32, block_size=64)
    # under the tpu key the sweep runs; the blk_k=128 candidate straddles
    # the 64-slot block and must be skipped as failed, not crash the row
    best = DA.ensure_paged_decode_tuned(b=1, h=1, s=S, d=16,
                                        dtype=jnp.float32, block_size=64,
                                        iters=1, platform="tpu")
    assert best == 64
    entry = autotune._mem[autotune._key(
        autotune.PAGED_DECODE_KERNEL, 0, 0, S, 16, "float32", False,
        "tpu")]
    skipped = {f["blk_k"] for f in entry["detail"]["failed"]}
    assert skipped == {128}
    # resolution now serves the recorded edge for the same shape
    assert DA.paged_decode_blk_k_for(b=1, h=1, s=S, d=16,
                                     dtype=jnp.float32, block_size=64,
                                     platform="tpu") == best


def test_paged_runner_executes_and_matches_oracle():
    """The paged sweep/microbench unit drives the REAL kernel on a full
    identity-table pool; its output must match the dense oracle built
    from the same seeded operands."""
    fn = DA.make_paged_decode_runner(16, b=1, h=2, s=64, d=16,
                                     dtype=jnp.float32, block_size=16)
    out = jax.block_until_ready(fn())
    assert out.shape == (1, 1, 2, 16)
    keys = jax.random.split(jax.random.PRNGKey(0), 3)
    q = jax.random.normal(keys[0], (1, 1, 2, 16), jnp.float32)
    kf = jax.random.normal(keys[1], (5, 2, 16, 16), jnp.float32)
    vf = jax.random.normal(keys[2], (5, 2, 16, 16), jnp.float32)
    kd = jnp.concatenate([kf[j] for j in range(4)], axis=1)[None]
    vd = jnp.concatenate([vf[j] for j in range(4)], axis=1)[None]
    scores = jnp.einsum("bqhd,bhkd->bhqk", q, kd) / jnp.sqrt(16.0)
    ref = jnp.einsum("bhqk,bhkd->bqhd", jax.nn.softmax(scores, -1), vd)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=1e-5, rtol=1e-5)
