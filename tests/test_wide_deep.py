"""Config-4 coverage: Wide&Deep, async PS → sync DP (semantic delta in
docs/async_ps_semantics.md)."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
from flax.training import train_state

from distributed_tensorflow_guide_tpu.data.synthetic import SyntheticCTR
from distributed_tensorflow_guide_tpu.models.wide_deep import WideDeep, make_loss_fn
from distributed_tensorflow_guide_tpu.parallel.data_parallel import DataParallel

VOCABS = (50, 50, 20)


def _init():
    model = WideDeep(vocab_sizes=VOCABS, num_dense=4, embed_dim=8, mlp_dims=(32,))
    data = SyntheticCTR(64, vocab_sizes=VOCABS, num_dense=4, seed=0)
    b = data.take(1)[0]
    params = model.init(
        jax.random.PRNGKey(0), jnp.asarray(b["cat"]), jnp.asarray(b["dense"])
    )["params"]
    return model, params, data


def test_forward_shape():
    model, params, data = _init()
    b = data.take(1)[0]
    out = model.apply({"params": params}, jnp.asarray(b["cat"]),
                      jnp.asarray(b["dense"]))
    assert out.shape == (64,) and out.dtype == jnp.float32


def test_dp_training_learns_ctr(mesh8):
    model, params, data = _init()
    dp = DataParallel(mesh8)
    state = dp.replicate(
        train_state.TrainState.create(
            apply_fn=model.apply, params=params, tx=optax.adam(5e-3)
        )
    )
    step = dp.make_train_step(make_loss_fn(model), donate=False)
    losses, accs = [], []
    for b in data.take(80):
        state, m = step(state, dp.shard_batch(b))
        losses.append(float(m["loss"]))
        accs.append(float(m["accuracy"]))
    # labels are sampled Bernoulli(p), so loss floors at the label entropy —
    # assert clear movement toward it plus above-chance accuracy
    assert np.mean(losses[-10:]) < np.mean(losses[:10]) * 0.92, (
        np.mean(losses[:10]), np.mean(losses[-10:]))
    assert np.mean(accs[-10:]) > 0.6


def test_embedding_grads_are_dense_and_synced(mesh8):
    """The PS inversion: embedding tables get dense pmean'd grads — verify a
    table actually moves under DP training (no stale PS rows)."""
    model, params, data = _init()
    dp = DataParallel(mesh8)
    state = dp.replicate(
        train_state.TrainState.create(
            apply_fn=model.apply, params=params, tx=optax.sgd(0.5)
        )
    )
    before = np.asarray(state.params["emb_0"]["embedding"]).copy()
    step = dp.make_train_step(make_loss_fn(model), donate=False)
    for b in data.take(3):
        state, _ = step(state, dp.shard_batch(b))
    after = np.asarray(state.params["emb_0"]["embedding"])
    assert not np.allclose(before, after)


def test_wide_deep_fsdp_shards_embedding_tables():
    """The reference's PS shards the big embedding tables across PS tasks
    (parameter_server_strategy_v2.py round-robins variables); FSDP is the
    TPU expression of the same placement — each 100k-row table lives
    1/world per device — with sync-DP numerics (loss parity below)."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from distributed_tensorflow_guide_tpu.core.mesh import (
        MeshSpec,
        build_mesh,
    )

    from distributed_tensorflow_guide_tpu.parallel.fsdp import FSDP

    vocabs = (100_000, 8_000)
    model = WideDeep(vocab_sizes=vocabs, num_dense=4, embed_dim=8,
                     mlp_dims=(32,))
    data = SyntheticCTR(32, vocab_sizes=vocabs, num_dense=4)
    b0 = data.take(1)[0]
    mesh = build_mesh(MeshSpec(data=-1))
    fsdp = FSDP(mesh)

    def init_fn():
        return model.init(jax.random.PRNGKey(0), jnp.asarray(b0["cat"]),
                          jnp.asarray(b0["dense"]))["params"]

    params, shardings = fsdp.init_params(init_fn)
    # the PS-analogue placement: big tables sharded over their vocab rows
    emb = params["emb_0"]["embedding"]
    assert tuple(emb.sharding.spec) == ("data", None)
    assert emb.addressable_shards[0].data.shape[0] == vocabs[0] // 8

    state = train_state.TrainState.create(
        apply_fn=model.apply, params=params, tx=optax.adam(1e-3))
    st_sh = fsdp.state_shardings(state, shardings)
    state = jax.device_put(state, st_sh)
    step_f = fsdp.make_train_step(make_loss_fn(model), st_sh, donate=False)

    # replicated-DP reference from the SAME initial params
    dp = DataParallel(mesh)
    params_np = jax.tree.map(np.asarray, params)
    state_d = dp.replicate(train_state.TrainState.create(
        apply_fn=model.apply, params=params_np, tx=optax.adam(1e-3)))
    step_d = dp.make_train_step(make_loss_fn(model), donate=False)

    for b in data.take(4):
        state, m_f = step_f(state, jax.device_put(
            b, NamedSharding(mesh, P("data"))))
        state_d, m_d = step_d(state_d, dp.shard_batch(b))
        np.testing.assert_allclose(float(m_f["loss"]), float(m_d["loss"]),
                                   rtol=1e-4)
