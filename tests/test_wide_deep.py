"""Config-4 coverage: Wide&Deep, async PS → sync DP (semantic delta in
docs/async_ps_semantics.md)."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
from flax.training import train_state

from distributed_tensorflow_guide_tpu.data.synthetic import SyntheticCTR
from distributed_tensorflow_guide_tpu.models.wide_deep import WideDeep, make_loss_fn
from distributed_tensorflow_guide_tpu.parallel.data_parallel import DataParallel

VOCABS = (50, 50, 20)


def _init():
    model = WideDeep(vocab_sizes=VOCABS, num_dense=4, embed_dim=8, mlp_dims=(32,))
    data = SyntheticCTR(64, vocab_sizes=VOCABS, num_dense=4, seed=0)
    b = data.take(1)[0]
    params = model.init(
        jax.random.PRNGKey(0), jnp.asarray(b["cat"]), jnp.asarray(b["dense"])
    )["params"]
    return model, params, data


def test_forward_shape():
    model, params, data = _init()
    b = data.take(1)[0]
    out = model.apply({"params": params}, jnp.asarray(b["cat"]),
                      jnp.asarray(b["dense"]))
    assert out.shape == (64,) and out.dtype == jnp.float32


def test_dp_training_learns_ctr(mesh8):
    model, params, data = _init()
    dp = DataParallel(mesh8)
    state = dp.replicate(
        train_state.TrainState.create(
            apply_fn=model.apply, params=params, tx=optax.adam(5e-3)
        )
    )
    step = dp.make_train_step(make_loss_fn(model), donate=False)
    losses, accs = [], []
    for b in data.take(80):
        state, m = step(state, dp.shard_batch(b))
        losses.append(float(m["loss"]))
        accs.append(float(m["accuracy"]))
    # labels are sampled Bernoulli(p), so loss floors at the label entropy —
    # assert clear movement toward it plus above-chance accuracy
    assert np.mean(losses[-10:]) < np.mean(losses[:10]) * 0.92, (
        np.mean(losses[:10]), np.mean(losses[-10:]))
    assert np.mean(accs[-10:]) > 0.6


def test_embedding_grads_are_dense_and_synced(mesh8):
    """The PS inversion: embedding tables get dense pmean'd grads — verify a
    table actually moves under DP training (no stale PS rows)."""
    model, params, data = _init()
    dp = DataParallel(mesh8)
    state = dp.replicate(
        train_state.TrainState.create(
            apply_fn=model.apply, params=params, tx=optax.sgd(0.5)
        )
    )
    before = np.asarray(state.params["emb_0"]["embedding"]).copy()
    step = dp.make_train_step(make_loss_fn(model), donate=False)
    for b in data.take(3):
        state, _ = step(state, dp.shard_batch(b))
    after = np.asarray(state.params["emb_0"]["embedding"])
    assert not np.allclose(before, after)
