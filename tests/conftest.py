"""Test harness: 8 virtual CPU devices in one process.

This is the JAX analogue of the TF in-process fake cluster
(tensorflow/python/framework/test_util.py create_local_cluster /
tensorflow/python/distribute/multi_worker_test_base.py
create_in_process_cluster): real collective semantics, no real fabric.
Env must be set before jax initializes its backends, hence module top-level.
"""

import os

# Force CPU regardless of the ambient JAX_PLATFORMS (the machine exports
# JAX_PLATFORMS=axon for the real chip; tests always run on fake devices).
os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402
import pytest  # noqa: E402

from distributed_tensorflow_guide_tpu.core import compat  # noqa: E402

# The axon PJRT plugin re-asserts its platform during `import jax`, so the
# config must be pinned post-import as well. The device count goes through
# the compat seam: JAX 0.9 has the jax_num_cpu_devices config, 0.4.x only
# honors the XLA flag exported above (set before first import — which is
# why this file must be imported before anything touches a backend).
jax.config.update("jax_platforms", "cpu")
compat.set_cpu_device_count(8)


@pytest.fixture(scope="session")
def devices():
    devs = jax.devices()
    assert len(devs) == 8, f"expected 8 fake devices, got {len(devs)}"
    return devs


@pytest.fixture()
def mesh8():
    from distributed_tensorflow_guide_tpu.core.mesh import MeshSpec, build_mesh

    return build_mesh(MeshSpec(data=-1))


@pytest.fixture()
def rng():
    return jax.random.PRNGKey(0)


@pytest.fixture()
def isolated_autotune_table(tmp_path, monkeypatch):
    """An empty in-memory autotune table redirected to a tmp file — nothing
    leaks between tests or to the user cache. One definition (round 9) for
    the fixtures test_autotune / test_fused_ce / test_overlap all declare
    autouse wrappers around."""
    from distributed_tensorflow_guide_tpu.ops import autotune

    monkeypatch.setenv("DTG_AUTOTUNE_TABLE", str(tmp_path / "table.json"))
    autotune.reset()
    yield autotune
    autotune.reset()
