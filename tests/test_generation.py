"""KV-cache generation parity (models/generation.py).

The serving path must be the SAME function the training path computes:
prefill logits equal the full training forward's logits, and greedy
decode equals re-scoring the growing prefix with the training model each
step (the O(S^2) oracle the cache exists to avoid)."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_tensorflow_guide_tpu.models.generation import (
    decode_config,
    init_cache,
    make_generate_fn,
)
from distributed_tensorflow_guide_tpu.models.transformer import (
    Transformer,
    TransformerConfig,
)

CFG = TransformerConfig(
    vocab_size=97, num_layers=2, num_heads=2, d_model=32, d_ff=64,
    max_len=32, causal=True, dtype=jnp.float32)


@pytest.fixture(scope="module")
def params():
    model = Transformer(CFG)
    toks = jnp.zeros((1, CFG.max_len), jnp.int32)
    return model.init(jax.random.PRNGKey(0), toks)["params"]


def test_prefill_logits_match_training_forward(params):
    model = Transformer(CFG)
    dmodel = Transformer(decode_config(CFG))
    prompt = np.random.RandomState(0).randint(0, CFG.vocab_size,
                                              (3, 7)).astype(np.int32)
    want = model.apply({"params": params}, prompt)  # (3, 7, V)
    cache = init_cache(CFG, params, 3)
    got, _ = dmodel.apply({"params": params, "cache": cache}, prompt, 0,
                          mutable=["cache"])
    np.testing.assert_allclose(got, want, rtol=2e-5, atol=2e-5)


def test_greedy_decode_matches_prefix_rescoring(params):
    model = Transformer(CFG)
    N = 6
    gen = make_generate_fn(CFG, max_new_tokens=N, temperature=0.0)
    prompt = np.random.RandomState(1).randint(0, CFG.vocab_size,
                                              (2, 5)).astype(np.int32)
    out = np.asarray(gen(params, prompt, jax.random.PRNGKey(0)))
    assert out.shape == (2, 5 + N)
    np.testing.assert_array_equal(out[:, :5], prompt)

    # oracle: full training forward on the growing prefix, argmax each step
    seq = prompt
    for _ in range(N):
        logits = model.apply({"params": params}, seq)
        nxt = np.asarray(jnp.argmax(logits[:, -1], -1), np.int32)
        seq = np.concatenate([seq, nxt[:, None]], axis=1)
    np.testing.assert_array_equal(out, seq)


def test_sampled_decode_reproducible_and_in_range(params):
    gen = make_generate_fn(CFG, max_new_tokens=4, temperature=0.8, top_k=10)
    prompt = np.zeros((2, 3), np.int32)
    a = np.asarray(gen(params, prompt, jax.random.PRNGKey(7)))
    b = np.asarray(gen(params, prompt, jax.random.PRNGKey(7)))
    c = np.asarray(gen(params, prompt, jax.random.PRNGKey(8)))
    np.testing.assert_array_equal(a, b)  # same rng -> same tokens
    assert (a >= 0).all() and (a < CFG.vocab_size).all()
    assert not np.array_equal(a, c)  # different rng varies (overwhelmingly)


def test_generate_rejects_overlong(params):
    gen = make_generate_fn(CFG, max_new_tokens=30)
    prompt = np.zeros((1, 5), np.int32)
    with pytest.raises(ValueError, match="max_len"):
        gen(params, prompt, jax.random.PRNGKey(0))


def test_decode_requires_index(params):
    dmodel = Transformer(decode_config(CFG))
    cache = init_cache(CFG, params, 1)
    with pytest.raises(ValueError, match="index"):
        dmodel.apply({"params": params, "cache": cache},
                     jnp.zeros((1, 1), jnp.int32), mutable=["cache"])


def test_single_new_token(params):
    gen = make_generate_fn(CFG, max_new_tokens=1, temperature=0.0)
    prompt = np.zeros((2, 4), np.int32)
    out = np.asarray(gen(params, prompt, jax.random.PRNGKey(0)))
    assert out.shape == (2, 5)


def test_decode_cache_donation_safety(params):
    """The donated-cache decode path (donate_cache=True, the default) under
    the buffer-reuse oracle pattern of tests/test_prefetch.py: every call
    allocates a FRESH cache and donates it into the compiled program, so a
    later call reusing the first call's buffers cannot corrupt results —
    repeated identical calls must be bit-identical, and must match the
    non-donating build.

    On the CPU test backend donation is gated OFF inside make_generate_fn
    (jax warns and ignores it there), so here the value-parity half runs
    on two identical programs; the WIRING is what this test can pin —
    ``donates_cache`` must reflect the knob x backend — and the aliasing
    itself is exercised on real hardware (battery ``gpt2_decode``)."""
    prompt = np.random.RandomState(5).randint(0, CFG.vocab_size,
                                              (2, 4)).astype(np.int32)
    gen = make_generate_fn(CFG, max_new_tokens=6, temperature=0.0,
                           donate_cache=True)
    assert gen.donates_cache == (jax.default_backend() != "cpu")
    a = np.asarray(gen(params, prompt, jax.random.PRNGKey(0)))
    b = np.asarray(gen(params, prompt, jax.random.PRNGKey(0)))
    np.testing.assert_array_equal(a, b)
    no_donate = make_generate_fn(CFG, max_new_tokens=6, temperature=0.0,
                                 donate_cache=False)
    assert no_donate.donates_cache is False
    c = np.asarray(no_donate(params, prompt, jax.random.PRNGKey(0)))
    np.testing.assert_array_equal(a, c)


def test_decode_unroll_parity(params):
    """The scan-unroll knob is an execution-shape change only: greedy AND
    sampled decode produce identical tokens at any unroll (including one
    that does not divide the step count)."""
    prompt = np.random.RandomState(6).randint(0, CFG.vocab_size,
                                              (2, 3)).astype(np.int32)
    # greedy at unroll 4; sampled (rng threading) at unroll 3, which does
    # NOT divide the 5-step decode loop — the remainder-handling case
    for kw, unroll in ((dict(temperature=0.0), 4),
                       (dict(temperature=0.8, top_k=10), 3)):
        base = make_generate_fn(CFG, max_new_tokens=6, **kw)
        want = np.asarray(base(params, prompt, jax.random.PRNGKey(1)))
        genu = make_generate_fn(CFG, max_new_tokens=6, unroll=unroll, **kw)
        got = np.asarray(genu(params, prompt, jax.random.PRNGKey(1)))
        np.testing.assert_array_equal(got, want)


def test_decode_hbm_bytes_model(params):
    """The decode-roofline byte model (bench_generate's denominator) in
    closed form: non-embedding params once + GATHERED embedding rows (B
    token rows + 1 position row, not the whole tables) + full KV cache
    read + one-slot write — then the two round-11 refinements: the int8
    cache halves the KV term (values at 1 byte + the per-slot f32 scales),
    and ``effective_len`` charges only the live block-rounded slots the
    length-aware kernel actually reads (full-``max_len`` charging is only
    correct for the dense static-shape path)."""
    from distributed_tensorflow_guide_tpu.models.generation import (
        decode_cache_bytes_per_step,
        decode_hbm_bytes_per_step,
    )

    B = 3
    got = decode_hbm_bytes_per_step(CFG, params, B)

    def nbytes(tree):
        return sum(leaf.size * np.dtype(leaf.dtype).itemsize
                   for leaf in jax.tree.leaves(tree))

    tables = nbytes(params["tok_emb"]) + nbytes(params["pos_emb"])
    gathered = (B + 1) * CFG.d_model * 4  # f32 embedding rows
    item = np.dtype(CFG.dtype).itemsize
    kv = CFG.num_layers * 2 * B * CFG.max_len * CFG.num_heads \
        * (CFG.d_model // CFG.num_heads) * item
    assert got == nbytes(params) - tables + gathered + kv + kv // CFG.max_len

    base = got - kv - kv // CFG.max_len  # the non-cache terms
    hd = CFG.d_model // CFG.num_heads
    # int8: 1-byte values + two f32 scales per (slot, head), read over the
    # full length + one-slot write — the VALUE bytes are kv/item (halved
    # vs bf16, quartered vs this f32 test config)
    icfg = dataclasses.replace(CFG, kv_dtype="int8")
    scales = CFG.num_layers * B * CFG.num_heads * 8  # 2 x f32, per slot
    kv8 = kv // item + scales * CFG.max_len
    want8 = base + kv8 + kv8 // CFG.max_len
    assert decode_hbm_bytes_per_step(icfg, params, B) == want8
    # effective_len scales ONLY the read term; the one-slot write stays
    L = 24
    wantL = base + kv * L // CFG.max_len + kv // CFG.max_len
    assert decode_hbm_bytes_per_step(CFG, params, B,
                                     effective_len=L) == wantL
    # the cache-only helper is exactly the cache terms of the full model
    assert decode_cache_bytes_per_step(CFG, B) == kv + kv // CFG.max_len
    assert decode_cache_bytes_per_step(
        icfg, B, effective_len=L) == (kv // item // CFG.max_len + scales
                                      ) * (L + 1)
    # the acceptance-gate claim in closed form: at the serving dtype
    # (bf16), int8 HALVES the cache value bytes; the f32 scale rows are
    # the only addition
    bcfg = dataclasses.replace(CFG, dtype=jnp.bfloat16)
    b16 = decode_cache_bytes_per_step(bcfg, B)
    b8 = decode_cache_bytes_per_step(
        dataclasses.replace(bcfg, kv_dtype="int8"), B)
    assert b8 == b16 / 2 + scales * (CFG.max_len + 1)


# ---- round-11 decode levers: int8 KV cache, Pallas decode-attend, -----------
# ---- self-speculative decoding ----------------------------------------------


def _greedy_tokens(cfg, params, prompt, n=6):
    gen = make_generate_fn(cfg, max_new_tokens=n, temperature=0.0)
    return np.asarray(gen(params, prompt, jax.random.PRNGKey(0)))


def test_int8_kv_decode_parity(params):
    """The quantized cache is an approximation with a pinned tolerance:
    decode-mode prefill logits stay close to the exact-cache logits, and
    greedy decode emits the same tokens on this config (logit gaps dwarf
    the <= scale/2 per-element quantization error)."""
    icfg = dataclasses.replace(CFG, kv_dtype="int8")
    prompt = np.random.RandomState(11).randint(0, CFG.vocab_size,
                                               (2, 6)).astype(np.int32)
    want, _ = Transformer(decode_config(CFG)).apply(
        {"params": params, "cache": init_cache(CFG, params, 2)}, prompt, 0,
        mutable=["cache"])
    got, _ = Transformer(decode_config(icfg)).apply(
        {"params": params, "cache": init_cache(icfg, params, 2)}, prompt, 0,
        mutable=["cache"])
    np.testing.assert_allclose(got, want, atol=0.05, rtol=0.05)
    np.testing.assert_array_equal(_greedy_tokens(icfg, params, prompt),
                                  _greedy_tokens(CFG, params, prompt))


def test_pallas_decode_generate_matches_dense(params):
    """End-to-end generate with decode_impl='pallas' (interpret mode on
    CPU) emits the same greedy tokens as the dense path — with and without
    the quantized cache."""
    prompt = np.random.RandomState(12).randint(0, CFG.vocab_size,
                                               (2, 4)).astype(np.int32)
    want = _greedy_tokens(CFG, params, prompt)
    pcfg = dataclasses.replace(CFG, decode_impl="pallas")
    np.testing.assert_array_equal(_greedy_tokens(pcfg, params, prompt),
                                  want)
    ipcfg = dataclasses.replace(CFG, decode_impl="pallas", kv_dtype="int8")
    icfg = dataclasses.replace(CFG, decode_impl="dense", kv_dtype="int8")
    np.testing.assert_array_equal(_greedy_tokens(ipcfg, params, prompt),
                                  _greedy_tokens(icfg, params, prompt))


def test_decode_cache_donation_safety_quantized(params):
    """The donation-safety contract extends to the QUANTIZED cache tree
    (int8 values + f32 scales, kernel layout): fresh-cache-per-call keeps
    repeated donated calls bit-identical and equal to the non-donating
    build; ``donates_cache`` reflects knob x backend as before."""
    icfg = dataclasses.replace(CFG, kv_dtype="int8", decode_impl="pallas")
    # the quantized tree really is what generate allocates
    from distributed_tensorflow_guide_tpu.models.generation import (
        cache_shapes,
    )

    leaves = jax.tree.leaves(cache_shapes(icfg, 2))
    dtypes = sorted({str(leaf.dtype) for leaf in leaves})
    assert dtypes == ["float32", "int8"]  # values int8, scales f32
    prompt = np.random.RandomState(13).randint(0, CFG.vocab_size,
                                               (2, 4)).astype(np.int32)
    gen = make_generate_fn(icfg, max_new_tokens=6, temperature=0.0,
                           donate_cache=True)
    assert gen.donates_cache == (jax.default_backend() != "cpu")
    a = np.asarray(gen(params, prompt, jax.random.PRNGKey(0)))
    b = np.asarray(gen(params, prompt, jax.random.PRNGKey(0)))
    np.testing.assert_array_equal(a, b)
    no_donate = make_generate_fn(icfg, max_new_tokens=6, temperature=0.0,
                                 donate_cache=False)
    assert no_donate.donates_cache is False
    c = np.asarray(no_donate(params, prompt, jax.random.PRNGKey(0)))
    np.testing.assert_array_equal(a, c)


def test_greedy_speculative_bitwise_identical_to_vanilla(params):
    """THE speculative pin: greedy speculative output is bitwise the
    vanilla greedy output — every emitted token is the verifier's own
    argmax for its position given an all-accepted prefix, so the schedule
    reorders the same argmaxes it would have computed one at a time."""
    prompt = np.random.RandomState(14).randint(0, CFG.vocab_size,
                                               (2, 5)).astype(np.int32)
    base = make_generate_fn(CFG, max_new_tokens=8, temperature=0.0)
    want = np.asarray(base(params, prompt, jax.random.PRNGKey(0)))
    # two lookaheads: the degenerate G=1 and the default G=4 (the full
    # K x G grid lives in the slow-marked composition test — tier-1
    # wall-clock budget)
    for lookahead in (1, 4):
        gen = make_generate_fn(CFG, max_new_tokens=8, temperature=0.0,
                               spec_draft_layers=1,
                               spec_lookahead=lookahead)
        got = np.asarray(gen(params, prompt, jax.random.PRNGKey(0)))
        np.testing.assert_array_equal(got, want,
                                      err_msg=f"lookahead {lookahead}")
        stats = {k: int(v) for k, v in gen.last_stats.items()}
        assert stats["verify_steps"] >= 1
        assert 0 <= stats["accepted_drafts"] <= 7


def test_sampled_speculative_identical_to_vanilla(params):
    """Sampling keys derive from the absolute position (Gumbel coupling),
    so the speculative schedule reproduces the SAMPLED vanilla stream too
    — same rng, same tokens, at any acceptance rate."""
    prompt = np.random.RandomState(15).randint(0, CFG.vocab_size,
                                               (2, 4)).astype(np.int32)
    base = make_generate_fn(CFG, max_new_tokens=7, temperature=0.8,
                            top_k=10)
    want = np.asarray(base(params, prompt, jax.random.PRNGKey(9)))
    gen = make_generate_fn(CFG, max_new_tokens=7, temperature=0.8,
                           top_k=10, spec_draft_layers=1, spec_lookahead=3)
    got = np.asarray(gen(params, prompt, jax.random.PRNGKey(9)))
    np.testing.assert_array_equal(got, want)


@pytest.mark.slow
def test_speculative_levers_compose_across_depths():
    """Exhaustive (draft depth x lookahead) grid on a 4-layer model, plus
    all three levers at once — multi-second (each cell compiles its own
    while-loop program), so tier-1 carries the fast pins above instead."""
    cfg = dataclasses.replace(CFG, num_layers=4)
    model = Transformer(cfg)
    params4 = model.init(jax.random.PRNGKey(1),
                         jnp.zeros((1, cfg.max_len), jnp.int32))["params"]
    prompt = np.random.RandomState(16).randint(0, cfg.vocab_size,
                                               (2, 5)).astype(np.int32)
    want = _greedy_tokens(cfg, params4, prompt, n=8)

    def spec_tokens(c, k, g):
        gen = make_generate_fn(c, max_new_tokens=8, temperature=0.0,
                               spec_draft_layers=k, spec_lookahead=g)
        return np.asarray(gen(params4, prompt, jax.random.PRNGKey(0)))

    for k in (1, 2, 3):
        for g in (1, 4):
            np.testing.assert_array_equal(spec_tokens(cfg, k, g), want,
                                          err_msg=f"K={k} G={g}")
    allcfg = dataclasses.replace(cfg, kv_dtype="int8",
                                 decode_impl="pallas")
    ref = _greedy_tokens(allcfg, params4, prompt, n=8)
    np.testing.assert_array_equal(spec_tokens(allcfg, 2, 4), ref)


@pytest.mark.slow
def test_sharded_serving_composes_with_decode_levers(params):
    """The docs/serving.md claim, pinned: DP- and TP-sharded generate stay
    token-identical to the unsharded run with the round-11 levers on (the
    quantized cache + scales inherit the sharding; lockstep acceptance is
    replicated by construction). Multi-second — each lever combination
    compiles its own sharded program."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from distributed_tensorflow_guide_tpu.core.mesh import (
        MeshSpec,
        build_mesh,
    )

    mesh = build_mesh(MeshSpec(data=-1))
    prompt = np.random.RandomState(17).randint(
        0, CFG.vocab_size, (8, 4)).astype(np.int32)
    for kv, impl, k in (("int8", "pallas", 0), ("int8", "dense", 1)):
        cfg = dataclasses.replace(CFG, kv_dtype=kv, decode_impl=impl)
        gen = make_generate_fn(cfg, max_new_tokens=5, temperature=0.0,
                               spec_draft_layers=k)
        want = np.asarray(gen(params, prompt, jax.random.PRNGKey(0)))
        sharded = jax.device_put(prompt, NamedSharding(mesh, P("data")))
        repl = jax.device_put(params, NamedSharding(mesh, P()))
        got = np.asarray(gen(repl, sharded, jax.random.PRNGKey(0)))
        np.testing.assert_array_equal(got, want,
                                      err_msg=f"kv={kv} impl={impl} K={k}")

    # TP: heads sharded over "model" (Megatron rules), int8+pallas
    import flax.linen as nn
    from flax.linen import spmd

    from distributed_tensorflow_guide_tpu.parallel.tensor import (
        DEFAULT_RULES,
    )

    tmesh = build_mesh(MeshSpec(data=4, model=2))
    cfg = dataclasses.replace(CFG, kv_dtype="int8", decode_impl="pallas")
    gen = make_generate_fn(cfg, max_new_tokens=5, temperature=0.0)
    small = prompt[:2]
    want = np.asarray(gen(params, small, jax.random.PRNGKey(0)))
    dmodel = Transformer(decode_config(cfg))
    abstract = jax.eval_shape(
        lambda: dmodel.init(jax.random.PRNGKey(0),
                            jnp.zeros((1, 1), jnp.int32), 0))
    specs = nn.get_partition_spec(abstract)["params"]
    rules = tuple((kk, None if kk == "vocab" else v)
                  for kk, v in DEFAULT_RULES)
    tp_params = jax.device_put(
        params, spmd.logical_to_mesh_sharding(specs, tmesh, rules))
    got = np.asarray(gen(tp_params, small, jax.random.PRNGKey(0)))
    np.testing.assert_array_equal(got, want)


def test_speculative_validation(params):
    with pytest.raises(ValueError, match="strictly between"):
        make_generate_fn(CFG, max_new_tokens=4, temperature=0.0,
                         spec_draft_layers=CFG.num_layers)
    with pytest.raises(ValueError, match="spec_lookahead"):
        make_generate_fn(CFG, max_new_tokens=4, temperature=0.0,
                         spec_draft_layers=1, spec_lookahead=0)
    # the lookahead needs cache headroom past the vanilla budget
    gen = make_generate_fn(CFG, max_new_tokens=26, temperature=0.0,
                           spec_draft_layers=1, spec_lookahead=4)
    with pytest.raises(ValueError, match="max_len"):
        gen(params, np.zeros((1, 4), np.int32), jax.random.PRNGKey(0))


def test_default_decode_trace_hermetic_on_cpu(params):
    """The tier-1 hermeticity pin: on the CPU backend the DEFAULT decode
    config (decode_impl='auto', kv_dtype=None) traces byte-identically to
    the explicitly-pinned dense/unquantized config — no Pallas call, no
    quantization, no layout change can leak into CI programs by default."""
    from distributed_tensorflow_guide_tpu.analysis.walker import traced_text

    tok = jnp.zeros((2, 1), jnp.int32)

    def trace(cfg):
        model = Transformer(decode_config(cfg))
        cache = init_cache(cfg, params, 2)
        return traced_text(
            lambda p, t: model.apply({"params": p, "cache": cache}, t, 3,
                                     mutable=["cache"]), params, tok)

    default = trace(CFG)
    pinned = trace(dataclasses.replace(CFG, decode_impl="dense"))
    assert default == pinned
    assert "pallas" not in default and "convert_element_type[new_dtype=int8" \
        not in default


def test_generate_with_dp_sharded_prompts(params):
    """Data-parallel serving: prompts sharded over the data axis produce
    the same tokens as the unsharded run (generate is pure SPMD — the
    KV cache inherits the batch sharding)."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from distributed_tensorflow_guide_tpu.core.mesh import MeshSpec, build_mesh

    mesh = build_mesh(MeshSpec(data=-1))
    gen = make_generate_fn(CFG, max_new_tokens=5, temperature=0.0)
    prompt = np.random.RandomState(2).randint(
        0, CFG.vocab_size, (8, 4)).astype(np.int32)
    want = np.asarray(gen(params, prompt, jax.random.PRNGKey(0)))

    sharded_prompt = jax.device_put(
        prompt, NamedSharding(mesh, P("data")))
    repl_params = jax.device_put(params, NamedSharding(mesh, P()))
    got = np.asarray(gen(repl_params, sharded_prompt, jax.random.PRNGKey(0)))
    np.testing.assert_array_equal(got, want)


def test_generate_with_tp_sharded_params(params):
    """Model-parallel serving: TP-sharded params (Megatron logical rules)
    decode the same tokens — GSPMD shards the cache over heads and inserts
    the collectives; no generation-specific sharding code exists."""
    import flax.linen as nn
    from flax.linen import spmd

    from distributed_tensorflow_guide_tpu.core.mesh import MeshSpec, build_mesh
    from distributed_tensorflow_guide_tpu.models.generation import (
        decode_config,
    )
    from distributed_tensorflow_guide_tpu.parallel.tensor import DEFAULT_RULES

    mesh = build_mesh(MeshSpec(data=4, model=2))  # CFG has 2 heads
    gen = make_generate_fn(CFG, max_new_tokens=5, temperature=0.0)
    prompt = np.random.RandomState(3).randint(
        0, CFG.vocab_size, (2, 4)).astype(np.int32)
    want = np.asarray(gen(params, prompt, jax.random.PRNGKey(0)))

    # derive the TP shardings from the decode-mode module's logical names
    dmodel = Transformer(decode_config(CFG))
    abstract = jax.eval_shape(
        lambda: dmodel.init(jax.random.PRNGKey(0),
                            jnp.zeros((1, 1), jnp.int32), 0))
    specs = nn.get_partition_spec(abstract)["params"]
    # CFG's vocab (97) is deliberately non-divisible: keep vocab-sharded
    # tables replicated, shard heads/mlp — the interesting TP dims here
    rules = tuple((k, None if k == "vocab" else v) for k, v in DEFAULT_RULES)
    shardings = spmd.logical_to_mesh_sharding(specs, mesh, rules)
    tp_params = jax.device_put(params, shardings)
    got = np.asarray(gen(tp_params, prompt, jax.random.PRNGKey(0)))
    np.testing.assert_array_equal(got, want)
