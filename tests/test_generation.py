"""KV-cache generation parity (models/generation.py).

The serving path must be the SAME function the training path computes:
prefill logits equal the full training forward's logits, and greedy
decode equals re-scoring the growing prefix with the training model each
step (the O(S^2) oracle the cache exists to avoid)."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_tensorflow_guide_tpu.models.generation import (
    decode_config,
    init_cache,
    make_generate_fn,
)
from distributed_tensorflow_guide_tpu.models.transformer import (
    Transformer,
    TransformerConfig,
)

CFG = TransformerConfig(
    vocab_size=97, num_layers=2, num_heads=2, d_model=32, d_ff=64,
    max_len=32, causal=True, dtype=jnp.float32)


@pytest.fixture(scope="module")
def params():
    model = Transformer(CFG)
    toks = jnp.zeros((1, CFG.max_len), jnp.int32)
    return model.init(jax.random.PRNGKey(0), toks)["params"]


def test_prefill_logits_match_training_forward(params):
    model = Transformer(CFG)
    dmodel = Transformer(decode_config(CFG))
    prompt = np.random.RandomState(0).randint(0, CFG.vocab_size,
                                              (3, 7)).astype(np.int32)
    want = model.apply({"params": params}, prompt)  # (3, 7, V)
    cache = init_cache(CFG, params, 3)
    got, _ = dmodel.apply({"params": params, "cache": cache}, prompt, 0,
                          mutable=["cache"])
    np.testing.assert_allclose(got, want, rtol=2e-5, atol=2e-5)


def test_greedy_decode_matches_prefix_rescoring(params):
    model = Transformer(CFG)
    N = 6
    gen = make_generate_fn(CFG, max_new_tokens=N, temperature=0.0)
    prompt = np.random.RandomState(1).randint(0, CFG.vocab_size,
                                              (2, 5)).astype(np.int32)
    out = np.asarray(gen(params, prompt, jax.random.PRNGKey(0)))
    assert out.shape == (2, 5 + N)
    np.testing.assert_array_equal(out[:, :5], prompt)

    # oracle: full training forward on the growing prefix, argmax each step
    seq = prompt
    for _ in range(N):
        logits = model.apply({"params": params}, seq)
        nxt = np.asarray(jnp.argmax(logits[:, -1], -1), np.int32)
        seq = np.concatenate([seq, nxt[:, None]], axis=1)
    np.testing.assert_array_equal(out, seq)


def test_sampled_decode_reproducible_and_in_range(params):
    gen = make_generate_fn(CFG, max_new_tokens=4, temperature=0.8, top_k=10)
    prompt = np.zeros((2, 3), np.int32)
    a = np.asarray(gen(params, prompt, jax.random.PRNGKey(7)))
    b = np.asarray(gen(params, prompt, jax.random.PRNGKey(7)))
    c = np.asarray(gen(params, prompt, jax.random.PRNGKey(8)))
    np.testing.assert_array_equal(a, b)  # same rng -> same tokens
    assert (a >= 0).all() and (a < CFG.vocab_size).all()
    assert not np.array_equal(a, c)  # different rng varies (overwhelmingly)


def test_generate_rejects_overlong(params):
    gen = make_generate_fn(CFG, max_new_tokens=30)
    prompt = np.zeros((1, 5), np.int32)
    with pytest.raises(ValueError, match="max_len"):
        gen(params, prompt, jax.random.PRNGKey(0))


def test_decode_requires_index(params):
    dmodel = Transformer(decode_config(CFG))
    cache = init_cache(CFG, params, 1)
    with pytest.raises(ValueError, match="index"):
        dmodel.apply({"params": params, "cache": cache},
                     jnp.zeros((1, 1), jnp.int32), mutable=["cache"])


def test_single_new_token(params):
    gen = make_generate_fn(CFG, max_new_tokens=1, temperature=0.0)
    prompt = np.zeros((2, 4), np.int32)
    out = np.asarray(gen(params, prompt, jax.random.PRNGKey(0)))
    assert out.shape == (2, 5)


def test_decode_cache_donation_safety(params):
    """The donated-cache decode path (donate_cache=True, the default) under
    the buffer-reuse oracle pattern of tests/test_prefetch.py: every call
    allocates a FRESH cache and donates it into the compiled program, so a
    later call reusing the first call's buffers cannot corrupt results —
    repeated identical calls must be bit-identical, and must match the
    non-donating build.

    On the CPU test backend donation is gated OFF inside make_generate_fn
    (jax warns and ignores it there), so here the value-parity half runs
    on two identical programs; the WIRING is what this test can pin —
    ``donates_cache`` must reflect the knob x backend — and the aliasing
    itself is exercised on real hardware (battery ``gpt2_decode``)."""
    prompt = np.random.RandomState(5).randint(0, CFG.vocab_size,
                                              (2, 4)).astype(np.int32)
    gen = make_generate_fn(CFG, max_new_tokens=6, temperature=0.0,
                           donate_cache=True)
    assert gen.donates_cache == (jax.default_backend() != "cpu")
    a = np.asarray(gen(params, prompt, jax.random.PRNGKey(0)))
    b = np.asarray(gen(params, prompt, jax.random.PRNGKey(0)))
    np.testing.assert_array_equal(a, b)
    no_donate = make_generate_fn(CFG, max_new_tokens=6, temperature=0.0,
                                 donate_cache=False)
    assert no_donate.donates_cache is False
    c = np.asarray(no_donate(params, prompt, jax.random.PRNGKey(0)))
    np.testing.assert_array_equal(a, c)


def test_decode_unroll_parity(params):
    """The scan-unroll knob is an execution-shape change only: greedy AND
    sampled decode produce identical tokens at any unroll (including one
    that does not divide the step count)."""
    prompt = np.random.RandomState(6).randint(0, CFG.vocab_size,
                                              (2, 3)).astype(np.int32)
    # greedy at unroll 4; sampled (rng threading) at unroll 3, which does
    # NOT divide the 5-step decode loop — the remainder-handling case
    for kw, unroll in ((dict(temperature=0.0), 4),
                       (dict(temperature=0.8, top_k=10), 3)):
        base = make_generate_fn(CFG, max_new_tokens=6, **kw)
        want = np.asarray(base(params, prompt, jax.random.PRNGKey(1)))
        genu = make_generate_fn(CFG, max_new_tokens=6, unroll=unroll, **kw)
        got = np.asarray(genu(params, prompt, jax.random.PRNGKey(1)))
        np.testing.assert_array_equal(got, want)


def test_decode_hbm_bytes_model(params):
    """The decode-roofline byte model (bench_generate's denominator) in
    closed form: non-embedding params once + GATHERED embedding rows (B
    token rows + 1 position row, not the whole tables) + full KV cache
    read + one-slot write."""
    from distributed_tensorflow_guide_tpu.models.generation import (
        decode_hbm_bytes_per_step,
    )

    B = 3
    got = decode_hbm_bytes_per_step(CFG, params, B)

    def nbytes(tree):
        return sum(leaf.size * np.dtype(leaf.dtype).itemsize
                   for leaf in jax.tree.leaves(tree))

    tables = nbytes(params["tok_emb"]) + nbytes(params["pos_emb"])
    gathered = (B + 1) * CFG.d_model * 4  # f32 embedding rows
    item = np.dtype(CFG.dtype).itemsize
    kv = CFG.num_layers * 2 * B * CFG.max_len * CFG.num_heads \
        * (CFG.d_model // CFG.num_heads) * item
    assert got == nbytes(params) - tables + gathered + kv + kv // CFG.max_len


def test_generate_with_dp_sharded_prompts(params):
    """Data-parallel serving: prompts sharded over the data axis produce
    the same tokens as the unsharded run (generate is pure SPMD — the
    KV cache inherits the batch sharding)."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from distributed_tensorflow_guide_tpu.core.mesh import MeshSpec, build_mesh

    mesh = build_mesh(MeshSpec(data=-1))
    gen = make_generate_fn(CFG, max_new_tokens=5, temperature=0.0)
    prompt = np.random.RandomState(2).randint(
        0, CFG.vocab_size, (8, 4)).astype(np.int32)
    want = np.asarray(gen(params, prompt, jax.random.PRNGKey(0)))

    sharded_prompt = jax.device_put(
        prompt, NamedSharding(mesh, P("data")))
    repl_params = jax.device_put(params, NamedSharding(mesh, P()))
    got = np.asarray(gen(repl_params, sharded_prompt, jax.random.PRNGKey(0)))
    np.testing.assert_array_equal(got, want)


def test_generate_with_tp_sharded_params(params):
    """Model-parallel serving: TP-sharded params (Megatron logical rules)
    decode the same tokens — GSPMD shards the cache over heads and inserts
    the collectives; no generation-specific sharding code exists."""
    import flax.linen as nn
    from flax.linen import spmd

    from distributed_tensorflow_guide_tpu.core.mesh import MeshSpec, build_mesh
    from distributed_tensorflow_guide_tpu.models.generation import (
        decode_config,
    )
    from distributed_tensorflow_guide_tpu.parallel.tensor import DEFAULT_RULES

    mesh = build_mesh(MeshSpec(data=4, model=2))  # CFG has 2 heads
    gen = make_generate_fn(CFG, max_new_tokens=5, temperature=0.0)
    prompt = np.random.RandomState(3).randint(
        0, CFG.vocab_size, (2, 4)).astype(np.int32)
    want = np.asarray(gen(params, prompt, jax.random.PRNGKey(0)))

    # derive the TP shardings from the decode-mode module's logical names
    dmodel = Transformer(decode_config(CFG))
    abstract = jax.eval_shape(
        lambda: dmodel.init(jax.random.PRNGKey(0),
                            jnp.zeros((1, 1), jnp.int32), 0))
    specs = nn.get_partition_spec(abstract)["params"]
    # CFG's vocab (97) is deliberately non-divisible: keep vocab-sharded
    # tables replicated, shard heads/mlp — the interesting TP dims here
    rules = tuple((k, None if k == "vocab" else v) for k, v in DEFAULT_RULES)
    shardings = spmd.logical_to_mesh_sharding(specs, mesh, rules)
    tp_params = jax.device_put(params, shardings)
    got = np.asarray(gen(tp_params, prompt, jax.random.PRNGKey(0)))
    np.testing.assert_array_equal(got, want)
