"""Continuous regression gate (round 21, analysis/regress.py): the
persisted bench-history store and the measured-vs-modeled drift check.

Proven end-to-end on CPU: a synthetic history whose latest entry is
inflated past tolerance gets flagged with the CORRECT binding resource
and the golden-bless join for its program, a clean history passes, and
the store itself honors the never-raise / drop-corrupt-lines /
env-override contracts the battery driver depends on.
"""

import json

import pytest

from distributed_tensorflow_guide_tpu.analysis import regress


def _decode_result(frac: float) -> dict:
    """A bench_generate-shaped result line, memory-bound at ``frac`` of
    the HBM roofline (compute fraction pinned low)."""
    return {"metric": "gpt2_decode_throughput", "value": 1000.0 * frac,
            "unit": "tokens/sec", "hbm_roofline_frac": frac,
            "flop_roofline_frac": 0.03}


def _entry(frac: float, sha: str, *, row="gpt2_decode",
           program="serve_decode_step", kind="TPU v5 lite") -> dict:
    return regress.make_entry(row, _decode_result(frac),
                              device_kind=kind, git_rev=sha,
                              program=program, ts=0.0)


# ---- the store --------------------------------------------------------------


def test_history_path_env_override(monkeypatch, tmp_path):
    monkeypatch.setenv(regress.HISTORY_ENV, str(tmp_path))
    assert regress.history_path() == tmp_path / "history.jsonl"
    monkeypatch.setenv(regress.HISTORY_ENV, str(tmp_path / "x.jsonl"))
    assert regress.history_path() == tmp_path / "x.jsonl"
    monkeypatch.delenv(regress.HISTORY_ENV)
    assert regress.history_path().parts[-2] == regress.DEFAULT_DIRNAME


def test_append_load_roundtrip(monkeypatch, tmp_path):
    monkeypatch.setenv(regress.HISTORY_ENV, str(tmp_path))
    e = _entry(0.8, "aaa1111")
    assert regress.append_entry(e)
    assert regress.append_entry(_entry(0.79, "bbb2222"))
    got = regress.load_history()
    assert len(got) == 2 and got[0] == e
    assert got[0]["efficiency"] == pytest.approx(0.8)
    assert got[0]["bound"] == "memory"  # hbm frac > flop frac


def test_append_never_raises(tmp_path):
    """Best-effort contract: an unwritable destination returns False
    instead of raising (a bench must never fail over bookkeeping)."""
    blocker = tmp_path / "blocker"
    blocker.write_text("a file where a directory must go")
    assert regress.append_entry(_entry(0.8, "x"),
                               path=blocker / "history.jsonl") is False


def test_load_drops_corrupt_lines(monkeypatch, tmp_path):
    monkeypatch.setenv(regress.HISTORY_ENV, str(tmp_path))
    p = regress.history_path()
    p.parent.mkdir(parents=True, exist_ok=True)
    good = _entry(0.8, "aaa1111")
    p.write_text(json.dumps(good) + "\n"
                 + '{"truncated by a crashed run...\n'
                 + "not json at all\n"
                 + json.dumps(["a", "list"]) + "\n")
    assert regress.load_history() == [good]


def test_missing_file_is_empty_history(monkeypatch, tmp_path):
    monkeypatch.setenv(regress.HISTORY_ENV, str(tmp_path / "nowhere"))
    assert regress.load_history() == []
    assert regress.check_history()["ok"]


# ---- make_entry normalization -----------------------------------------------


def test_make_entry_prefers_recon_efficiency():
    """A result line carrying an obs.recon.reconcile output embeds the
    better evidence — efficiency + bound win over roofline fractions."""
    r = {"metric": "m", "value": 1.0, "unit": "u",
         "efficiency": 0.61, "bound": "pcie", "measured_s": 2.0,
         "model_time_s": 1.22, "hbm_roofline_frac": 0.9}
    e = regress.make_entry("row", r, device_kind="k", git_rev="s")
    assert e["efficiency"] == 0.61 and e["bound"] == "pcie"
    assert e["measured_s"] == 2.0 and e["model_time_s"] == 1.22


def test_make_entry_skip_and_bare_rows():
    skip = regress.make_entry("row", {"skipped": "no TPU"},
                              device_kind="k", git_rev="s")
    assert skip["skipped"] == "no TPU" and "efficiency" not in skip
    bare = regress.make_entry("row", {"metric": "m", "value": 1, "unit":
                                      "u"}, device_kind="k", git_rev="s")
    assert "efficiency" not in bare and "bound" not in bare


# ---- the gate ---------------------------------------------------------------


def test_clean_history_passes():
    rep = regress.check_history(
        [_entry(0.80, "a"), _entry(0.78, "b"), _entry(0.81, "c")])
    assert rep["ok"] and rep["n_checked"] == 1 and rep["flags"] == []


def test_inflated_entry_flagged_with_bound_and_bless_join():
    """The end-to-end acceptance pin: the latest entry running at half
    the historical HBM fraction (measured/modeled ratio 2x baseline)
    must flag, name 'memory' as the binding resource, and join the
    golden-fingerprint bless reason for the row's program."""
    rep = regress.check_history(
        [_entry(0.80, "aaa1111"), _entry(0.78, "bbb2222"),
         _entry(0.39, "ccc3333")])
    assert not rep["ok"] and len(rep["flags"]) == 1
    f = rep["flags"][0]
    assert f["row"] == "gpt2_decode" and f["bound"] == "memory"
    assert f["latest_git_sha"] == "ccc3333"
    assert f["drift"] > 0.25
    assert f["program"] == "serve_decode_step"
    # the join against analysis/golden_fingerprints.json: the shipped
    # golden for serve_decode_step carries a bless reason
    assert f.get("last_bless")
    # and the rendering names the resource + the reason
    text = regress.render_report(rep)
    assert "memory" in text and f["last_bless"] in text


def test_groups_are_per_device_kind():
    """One slow entry on a DIFFERENT device_kind is a new baseline, not
    a regression — no cross-device normalization by contract."""
    rep = regress.check_history(
        [_entry(0.80, "a"), _entry(0.78, "b"),
         _entry(0.39, "c", kind="TPU v6e")])
    assert rep["ok"]  # the v6e group has only one entry: nothing to gate


def test_skipped_entries_never_gate():
    skip = regress.make_entry("gpt2_decode", {"skipped": "row-timeout"},
                              device_kind="TPU v5 lite", git_rev="c")
    rep = regress.check_history([_entry(0.8, "a"), _entry(0.78, "b"),
                                 skip])
    assert rep["ok"]


def test_improvement_is_not_flagged():
    rep = regress.check_history(
        [_entry(0.40, "a"), _entry(0.41, "b"), _entry(0.80, "c")])
    assert rep["ok"]  # faster than baseline: not a regression


def test_tolerance_is_respected():
    entries = [_entry(0.80, "a"), _entry(0.80 / 1.2, "b")]  # +20% ratio
    assert regress.check_history(entries, tol=0.25)["ok"]
    assert not regress.check_history(entries, tol=0.15)["ok"]


# ---- selftest + CLI ---------------------------------------------------------


def test_selftest_passes():
    st = regress.selftest()
    assert st["ok"] and st["clean"]["ok"] and not st["inflated"]["ok"]


def test_cli_selftest_and_history(monkeypatch, tmp_path, capsys):
    monkeypatch.setenv(regress.HISTORY_ENV, str(tmp_path))
    assert regress.main(["--selftest"]) == 0
    for e in (_entry(0.8, "a"), _entry(0.39, "b")):
        regress.append_entry(e)
    assert regress.main(["--json"]) == 1
    rep = json.loads(capsys.readouterr().out.splitlines()[-1])
    assert not rep["ok"] and rep["flags"][0]["bound"] == "memory"
